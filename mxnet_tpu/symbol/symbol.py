"""Symbol: declarative graph construction API.

Parity: ``mx.sym`` (python/mxnet/symbol/symbol.py, 3,288 LoC) and the
nnvm graph IR it fronts.  TPU-native: a Symbol is a lightweight DAG of
registry-op nodes; *binding* it lowers the whole graph to one jitted
XLA executable (the reference's ``_bind`` → CachedOp Executor path,
python/mxnet/executor.py:25).  Shape/type inference is `jax.eval_shape`
over the same lowering — one mechanism instead of per-op FInferShape.

JSON (de)serialization mirrors the reference's symbol json (nodes /
arg_nodes / heads layout, src/nnvm/legacy_json_util.cc) so models can
be saved and re-loaded by name.
"""
from __future__ import annotations

import os
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as onp
import jax
import jax.numpy as jnp

from ..base import MXNetError, np_dtype
from ..ops import registry as _reg

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "trace"]

def _auto_name(op_name: str) -> str:
    # every auto name flows through the active NameManager (parity:
    # name.py NameManager): the default manager at the stack bottom
    # plays the global counter's role, a freshly entered manager
    # restarts numbering, and Prefix prepends
    from .. import name as _name_mod
    return _name_mod.current().get(None, op_name.lower().lstrip("_"))


class _Node:
    """One graph node: a free variable or an op application."""

    __slots__ = ("op_name", "name", "params", "inputs", "num_outputs",
                 "attrs")

    def __init__(self, op_name: Optional[str], name: str,
                 params: Optional[dict] = None,
                 inputs: Optional[List[Tuple["_Node", int]]] = None,
                 num_outputs: int = 1, attrs: Optional[dict] = None):
        self.op_name = op_name          # None → variable ("null" op)
        self.name = name
        self.params = dict(params or {})
        # user attributes merged from the active AttrScope (parity:
        # attribute.py AttrScope applied at symbol creation)
        from .. import attribute as _attr
        self.attrs = _attr.current().get(attrs)
        self.inputs = list(inputs or [])
        self.num_outputs = num_outputs

    @property
    def is_var(self) -> bool:
        return self.op_name is None


class Symbol:
    """A (possibly multi-output) reference into the graph."""

    def __init__(self, outputs: Sequence[Tuple[_Node, int]]):
        self._outputs: List[Tuple[_Node, int]] = list(outputs)

    # -- construction ------------------------------------------------------
    @property
    def name(self) -> str:
        return self._outputs[0][0].name

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def __iter__(self):
        return (Symbol([o]) for o in self._outputs)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            for node, i in _topo_order([o[0] for o in self._outputs]):
                if node.name == idx:
                    return Symbol([(node, 0)])
            raise MXNetError(f"no internal symbol named {idx!r}")
        if isinstance(idx, slice):
            return Group([Symbol([o]) for o in self._outputs[idx]])
        if idx < len(self._outputs):
            return Symbol([self._outputs[idx]])
        node, _ = self._outputs[0]
        if not node.is_var:
            # multi-output op (e.g. BatchNorm's aux outputs): select lazily
            return Symbol([(node, idx)])
        raise IndexError(idx)

    def __len__(self):
        return len(self._outputs)

    @property
    def num_outputs(self) -> int:
        return len(self._outputs)

    # -- graph introspection (parity: list_arguments/list_outputs) ---------
    def list_arguments(self) -> List[str]:
        aux = set(self.list_auxiliary_states())
        return [n.name for n in self._var_nodes() if n.name not in aux]

    def list_outputs(self) -> List[str]:
        out = []
        for node, i in self._outputs:
            suffix = "" if node.num_outputs == 1 else str(i)
            out.append(f"{node.name}_output{suffix}"
                       if not node.is_var else node.name)
        return out

    def list_inputs(self) -> List[str]:
        return [n.name for n in self._var_nodes()]

    def list_auxiliary_states(self) -> List[str]:
        """Variables consumed at an op's mutable-input positions
        (parity: FMutateInputs — e.g. BatchNorm's moving_mean/var,
        batch_norm.cc).  They take no gradient and are updated by the op
        itself."""
        aux, seen = [], set()
        for node in _topo_nodes([o[0] for o in self._outputs]):
            for pos in _AUX_INPUT_POS.get(node.op_name, ()):
                if pos < len(node.inputs):
                    src, _ = node.inputs[pos]
                    if src.is_var and src.name not in seen:
                        seen.add(src.name)
                        aux.append(src.name)
        return aux

    def _var_nodes(self) -> List[_Node]:
        return [n for n in _topo_nodes([o[0] for o in self._outputs])
                if n.is_var]

    def get_internals(self) -> "Symbol":
        nodes = _topo_nodes([o[0] for o in self._outputs])
        return Group([Symbol([(n, i)]) for n in nodes
                      for i in range(n.num_outputs)])

    def get_children(self) -> Optional["Symbol"]:
        node = self._outputs[0][0]
        if not node.inputs:
            return None
        return Group([Symbol([inp]) for inp in node.inputs])

    @property
    def attr_dict(self):
        return {n.name: {k: str(v) for k, v in n.params.items()}
                for n in _topo_nodes([o[0] for o in self._outputs])}

    def attr(self, key):
        """User attribute lookup on this symbol's head node (parity:
        symbol.attr)."""
        return self._outputs[0][0].attrs.get(key)

    def list_attr(self):
        """User attributes of the head node (parity: symbol.list_attr)."""
        return dict(self._outputs[0][0].attrs)

    # -- composition (parity: symbol call substitution) --------------------
    def __call__(self, **kwargs):
        """Substitute named variables with other symbols."""
        mapping = {}
        for name, sym in kwargs.items():
            if not isinstance(sym, Symbol):
                raise TypeError("compose expects Symbols")
            mapping[name] = sym._outputs[0]
        memo: Dict[int, _Node] = {}

        def edge(node: _Node, idx: int) -> Tuple[_Node, int]:
            if node.is_var and node.name in mapping:
                return mapping[node.name]  # carries its own output index
            return (rebuild(node), idx)

        def rebuild(node: _Node) -> _Node:
            if id(node) in memo:
                return memo[id(node)]
            new = _Node(node.op_name, node.name, node.params,
                        [edge(n, i) for n, i in node.inputs],
                        node.num_outputs)
            new.attrs = dict(node.attrs)   # not the ambient AttrScope
            memo[id(node)] = new
            return new

        return Symbol([edge(n, i) for n, i in self._outputs])

    # -- arithmetic --------------------------------------------------------
    def _binop(self, other, op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _apply(op, [a, b])
        # scalar: lift through the scalar-aware op lambda
        c = float(other)
        scalar_op = {"elemwise_add": "_plus_scalar",
                     "elemwise_sub": "_rminus_scalar" if reverse
                     else "_minus_scalar",
                     "elemwise_mul": "_mul_scalar",
                     "elemwise_div": "_rdiv_scalar" if reverse
                     else "_div_scalar",
                     "broadcast_power": "_rpower_scalar" if reverse
                     else "_power_scalar"}.get(op)
        if scalar_op and scalar_op in _reg._REGISTRY:
            return _apply(scalar_op, [self], scalar=c)
        return _apply(op, [self], _scalar=c, _reverse=reverse)

    def __add__(self, o): return self._binop(o, "elemwise_add")
    def __radd__(self, o): return self._binop(o, "elemwise_add", True)
    def __sub__(self, o): return self._binop(o, "elemwise_sub")
    def __rsub__(self, o): return self._binop(o, "elemwise_sub", True)
    def __mul__(self, o): return self._binop(o, "elemwise_mul")
    def __rmul__(self, o): return self._binop(o, "elemwise_mul", True)
    def __truediv__(self, o): return self._binop(o, "elemwise_div")
    def __rtruediv__(self, o): return self._binop(o, "elemwise_div", True)
    def __pow__(self, o): return self._binop(o, "broadcast_power")
    def __neg__(self): return _apply("negative", [self])

    def __eq__(self, o):
        return self._binop(o, "broadcast_equal") if isinstance(o, Symbol) \
            else NotImplemented
    __hash__ = object.__hash__

    # -- evaluation --------------------------------------------------------
    def _lower(self, arg_names: List[str], is_train: bool = True):
        """Build fn(list-of-arrays) -> list-of-output-arrays.

        ``is_train=False`` lowers the inference graph: train-only
        stochastic ops (Dropout with mode != "always") become identity
        — the executor analogue of the reference threading is_train
        into op runtimes."""
        order = _topo_nodes([o[0] for o in self._outputs])
        pos = {name: i for i, name in enumerate(arg_names)}

        def fn(arg_arrays):
            vals: Dict[int, Any] = {}
            for node in order:
                if node.is_var:
                    if node.name not in pos:
                        raise MXNetError(f"missing argument {node.name!r}")
                    vals[id(node)] = [arg_arrays[pos[node.name]]]
                else:
                    ins = [vals[id(n)][i] for n, i in node.inputs]
                    op = _reg.get(node.op_name)
                    if (not is_train and op.train_identity
                            and node.params.get("mode",
                                                "training") != "always"):
                        vals[id(node)] = [ins[0]]
                        continue
                    out = op.fn(*ins, **node.params)
                    vals[id(node)] = list(out) if isinstance(
                        out, (tuple, list)) else [out]
            return [vals[id(n)][i] for n, i in self._outputs]

        return fn

    def list_prng_keys(self) -> List[str]:
        """Names of auto-created PRNG-key variables (marked at symbol
        composition; the engine RNG resource in the reference)."""
        order = _topo_nodes([o[0] for o in self._outputs])
        return [n.name for n in order
                if n.is_var and n.attrs.get("__prng_key__")]

    def infer_shape(self, **kwargs):
        """Infer output shapes from argument shapes (parity:
        symbol.infer_shape — *partial* inference: parameter shapes
        omitted from kwargs are derived from data flow with per-op rules,
        the analogue of each reference op's FInferShape filling unknown
        weight dims)."""
        args = self.list_arguments()
        auxs = self.list_auxiliary_states()
        names = args + auxs
        known = {n: tuple(kwargs[n]) for n in names if n in kwargs}
        if len(known) < len(names):
            known = self._infer_missing_arg_shapes(known)
        keyset = set(self.list_prng_keys())
        structs = {}
        for name in names:
            if name in keyset:
                structs[name] = jax.ShapeDtypeStruct((2,), jnp.uint32)
                known.setdefault(name, (2,))
                continue
            if name not in known:
                raise MXNetError(f"infer_shape: cannot infer shape for "
                                 f"{name!r}; pass it explicitly")
            structs[name] = jax.ShapeDtypeStruct(known[name], jnp.float32)
        fn = self._lower(names)
        outs = jax.eval_shape(lambda a: fn(a), [structs[n] for n in names])
        out_shapes = [tuple(o.shape) for o in outs]
        return ([tuple(structs[n].shape) for n in args], out_shapes,
                [tuple(structs[n].shape) for n in auxs])

    def infer_shape_partial(self, **kwargs):
        """Best-effort variant returning None for arguments it cannot
        infer (parity: symbol.infer_shape_partial)."""
        try:
            return self.infer_shape(**kwargs)
        except Exception:   # jax.eval_shape raises raw TypeError/ValueError
            args = self.list_arguments()
            auxs = self.list_auxiliary_states()
            known = self._infer_missing_arg_shapes(
                {n: tuple(kwargs[n]) for n in args + auxs if n in kwargs})
            return ([known.get(n) for n in args], None,
                    [known.get(n) for n in auxs])

    def _infer_missing_arg_shapes(self, known):
        """Forward pass deriving parameter shapes from data shapes — the
        same rules each layer's deferred init uses (gluon Dense/_Conv
        _finish_deferred)."""
        known = dict(known)
        order = _topo_nodes([o[0] for o in self._outputs])
        shapes: Dict[int, Any] = {}   # id(node) -> list of out shapes
        for node in order:
            if node.is_var:
                if node.name in known:
                    shapes[id(node)] = [known[node.name]]
                continue
            in_shapes = []
            for pos, (src, i) in enumerate(node.inputs):
                lst = shapes.get(id(src))
                s = lst[i] if lst and i < len(lst) else (
                    lst[0] if lst else None)
                if s is None and src.is_var:
                    s = _param_shape_rule(node.op_name, pos,
                                          in_shapes[0] if in_shapes else None,
                                          node.params)
                    if s is not None:
                        known[src.name] = s
                        shapes[id(src)] = [s]
                in_shapes.append(s)
            if any(s is None for s in in_shapes):
                continue
            try:
                op = _reg.get(node.op_name)
                structs = [jax.ShapeDtypeStruct(s, jnp.float32)
                           for s in in_shapes]
                out = jax.eval_shape(
                    lambda *a: op.fn(*a, **node.params), *structs)
                outs = list(out) if isinstance(out, (tuple, list)) else [out]
                shapes[id(node)] = [tuple(o.shape) for o in outs]
            except Exception:
                continue
        return known

    def infer_type(self, **kwargs):
        args = self.list_arguments()
        structs = [jax.ShapeDtypeStruct((1,), np_dtype(kwargs.get(n)))
                   for n in args]
        return ([s.dtype for s in structs], [jnp.float32], [])

    def eval(self, ctx=None, **kwargs):
        from ..ndarray import NDArray
        from ..ops.random import next_key
        args = self.list_arguments() + self.list_auxiliary_states()
        keyset = set(self.list_prng_keys())
        fn = self._lower(args)
        arrays = []
        for name in args:
            if name not in kwargs:
                if name in keyset:   # auto-supplied engine RNG
                    arrays.append(next_key())
                    continue
                raise MXNetError(f"eval: missing argument {name!r}")
            v = kwargs[name]
            arrays.append(v._data if isinstance(v, NDArray)
                          else jnp.asarray(v))
        return [NDArray(o) for o in fn(arrays)]

    # -- binding (parity: simple_bind → Executor over CachedOp) ------------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from .executor import Executor
        sym = self
        # parity: MXNET_SUBGRAPH_BACKEND (env_var.md) — partition at
        # bind time with the named backend, as build_subgraph does in
        # src/executor/graph_executor.cc Init
        backend = os.environ.get("MXNET_SUBGRAPH_BACKEND", "")
        if backend and backend != "NONE":
            try:
                sym = self.optimize_for(backend)
            except MXNetError:
                pass  # unknown backend: bind unpartitioned, like the ref
        return Executor(sym, ctx, args, args_grad, grad_req,
                        aux_states=aux_states)

    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        from ..ndarray import NDArray
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        arg_shapes, _, aux_shapes = self.infer_shape(**shapes)
        keyset = set(self.list_prng_keys())
        args = {n: NDArray(onp.zeros(s, "float32"))
                for n, s in zip(arg_names, arg_shapes)
                if n not in keyset}       # keys: auto-supplied at bind
        grads = {n: NDArray(onp.zeros(s, "float32"))
                 for n, s in zip(arg_names, arg_shapes)
                 if n not in keyset} \
            if grad_req != "null" else None
        aux = {n: NDArray(onp.zeros(s, "float32"))
               for n, s in zip(aux_names, aux_shapes)}
        return self.bind(ctx, args, grads, grad_req, aux_states=aux)

    def optimize_for(self, backend: str, **options) -> "Symbol":
        """Partition the graph with a registered subgraph backend
        (parity: sym.optimize_for → build_subgraph pass)."""
        from ..subgraph import partition
        return partition(self, backend, **options)

    # -- serialization -----------------------------------------------------
    def tojson(self) -> str:
        nodes = _topo_nodes([o[0] for o in self._outputs])
        idx = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            spec = {
                "op": "null" if n.is_var else n.op_name,
                "name": n.name,
                "attrs": _json_attrs(n.params),
                "inputs": [[idx[id(src)], i, 0] for src, i in n.inputs],
            }
            if n.attrs:
                spec["user_attrs"] = dict(n.attrs)
            out_nodes.append(spec)
        payload = {
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_var],
            "heads": [[idx[id(n)], i, 0] for n, i in self._outputs],
            "attrs": {"mxnet_version": ["int", 20000],
                      "format": "mxnet_tpu-symbol-v1"},
        }
        return json.dumps(payload, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())


def _json_attrs(params: dict) -> dict:
    out = {}
    for k, v in params.items():
        if isinstance(v, tuple):
            out[k] = list(v)
        elif isinstance(v, (int, float, bool, str, type(None), list)):
            out[k] = v
        else:
            out[k] = str(v)
    return out


def _from_json_attrs(attrs: dict) -> dict:
    return {k: tuple(v) if isinstance(v, list) else v
            for k, v in attrs.items()}


def _topo_nodes(roots: List[_Node]) -> List[_Node]:
    seen, order = set(), []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for src, _ in node.inputs:
            visit(src)
        order.append(node)

    for r in roots:
        visit(r)
    return order


def _topo_order(roots: List[_Node]):
    return [(n, 0) for n in _topo_nodes(roots)]


def _apply(op_name: str, inputs: List[Symbol], name: Optional[str] = None,
           **params) -> Symbol:
    op = _reg.get(op_name)
    reverse = params.pop("_reverse", None)
    scalar = params.pop("_scalar", None)
    if scalar is not None:
        # wrap scalar into the op's params for lowering via a lambda op —
        # represent as an explicit broadcastable constant variable-free node
        params["__scalar__"] = scalar
        params["__reverse__"] = bool(reverse)
        op_name = "_scalar_wrap:" + op_name
        _ensure_scalar_wrap(op_name)
    from .. import name as _name_mod
    node_name = _name_mod.current().get(
        name, op_name.split(":")[-1].lower().lstrip("_"))
    node = _Node(op_name, node_name,
                 params, [(s._outputs[0][0], s._outputs[0][1])
                          for s in inputs],
                 num_outputs=1)
    n_out = _probe_num_outputs(op)
    node.num_outputs = n_out
    return Symbol([(node, i) for i in range(n_out)]) if n_out > 1 \
        else Symbol([(node, 0)])


def _ensure_scalar_wrap(wrapped_name: str):
    if wrapped_name in _reg._REGISTRY:
        return
    base = wrapped_name.split(":", 1)[1]
    base_fn = _reg.get(base).fn

    def fn(x, **params):
        c = params.pop("__scalar__")
        rev = params.pop("__reverse__", False)
        cv = jnp.asarray(c, x.dtype)
        return base_fn(cv, x, **params) if rev else base_fn(x, cv, **params)

    _reg._REGISTRY[wrapped_name] = _reg.Operator(wrapped_name, fn)


def _probe_num_outputs(op) -> int:
    return 1  # multi-out ops report 1 head; outputs split lazily on index


# kwargs the reference's sym.var() accepts directly and stringifies into
# __dunder__ attrs (parity: python/mxnet/symbol/symbol.py var())
_VAR_KNOWN_KWARGS = ("lr_mult", "wd_mult", "init", "stype",
                     "profiler_scope")


def Variable(name: str, shape=None, dtype=None, attrs=None,
             **kwargs) -> Symbol:
    merged = dict(attrs or {})
    for k, v in kwargs.items():
        if k in _VAR_KNOWN_KWARGS or (k.startswith("__") and k.endswith("__")):
            key = k if k.startswith("__") else f"__{k}__"
            if hasattr(v, "dumps"):  # Initializer → its JSON form
                merged[key] = v.dumps()
            else:
                merged[key] = v if isinstance(v, str) else str(v)
        else:
            merged[k] = v
    for k, v in merged.items():
        if not isinstance(v, str):
            raise ValueError(
                f"Attribute {k}={v!r}: attributes need to be strings "
                "(parity: symbol.Variable)")
    return Symbol([(_Node(None, name, attrs=merged), 0)])


var = Variable


def trace(block, *inputs):
    """Trace one imperative gluon forward into a Symbol graph.

    Returns ``(sym, arg_params, aux_params)``.  Runs ``block(*inputs)``
    under a deferred-compute scope (parity: the reference's deferred
    compute tracing, python/mxnet/_deferred_compute.py + Imperative
    DCInfo, src/imperative/imperative.cc): every eager op dispatch also
    records a graph node, so any model-zoo network — written purely
    imperatively — yields the Symbol graph that sym.bind, symbol json,
    and ONNX export consume.  Aux params (``grad_req == 'null'``, e.g.
    BatchNorm running stats) are split out as the reference does.
    """
    from .. import autograd as ag
    from ..base import MXNetError as _Err
    from ..ndarray import NDArray
    from ..ops import registry as _dcr

    nd_in = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
    was_active = bool(getattr(block, "_active", False))
    if was_active:
        block.hybridize(False)   # cached graphs bypass the dispatch funnel
    with ag.pause(train_mode=False):
        block(*nd_in)            # finish any deferred init eagerly
    params = dict(block.collect_params().items())

    def _tag(nd, name):
        nd._dc_sym = (_Node(None, name), 0)
        scope.touched.append(nd)

    scope = _dcr.DCScope()
    try:
        with scope:
            for k, p in params.items():
                _tag(p.data(), k)
            for i, x in enumerate(nd_in):
                _tag(x, "data" if len(nd_in) == 1 else f"data{i}")
            with ag.pause(train_mode=False):
                out = block(*nd_in)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        refs = []
        for o in outs:
            ref = getattr(o, "_dc_sym", None)
            if ref is None:
                raise _Err(
                    "symbol.trace: a block output was not produced by "
                    "registry ops — nothing was recorded for it")
            refs.append(ref)
        sym = Symbol(refs)
        used = {n.name for n in _topo_nodes([r[0] for r in refs])
                if n.is_var}
        arg_params, aux_params = {}, {}
        for k, p in params.items():
            if k in used:
                dst = aux_params if p.grad_req == "null" else arg_params
                dst[k] = p.data()
        for k, nd in scope.captured.items():
            if k in used:
                arg_params[k] = nd
        return sym, arg_params, aux_params
    finally:
        if was_active:
            block.hybridize(True)
        # clear EVERY tag laid down under this scope — including op
        # outputs a block may have cached on itself — so a later trace
        # never splices this trace's dead subgraph into its own
        for nd in scope.touched:
            try:
                del nd._dc_sym
            except AttributeError:
                pass
        scope.touched.clear()


def Group(symbols: Sequence[Symbol]) -> Symbol:
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


_KNOWN_FORMATS = ("mxnet_tpu-symbol-v1",)


def _parse_legacy_attr(v):
    """Reference symbol json stores every attr as a string ("(3, 3)",
    "True", "2") — parse back to Python values (parity:
    src/nnvm/legacy_json_util.cc attribute upgrade)."""
    if not isinstance(v, str):
        return v
    import ast
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return v


def load_json(json_str: str) -> Symbol:
    payload = json.loads(json_str)
    fmt = (payload.get("attrs") or {}).get("format")
    legacy = fmt is None        # reference-produced json has no format tag
    if not legacy and fmt not in _KNOWN_FORMATS:
        raise MXNetError(
            f"unknown symbol json format {fmt!r}; this build reads "
            f"{_KNOWN_FORMATS} and legacy (reference) symbol json")
    nodes: List[_Node] = []
    for spec in payload["nodes"]:
        if spec["op"] == "null":
            node = _Node(None, spec["name"])
        else:
            # older reference json stores attrs under "param"/"attr"
            raw = spec.get("attrs", spec.get("attr", spec.get("param", {})))
            if legacy:
                params = {k: _parse_legacy_attr(v) for k, v in raw.items()}
            else:
                params = _from_json_attrs(raw)
            if spec["op"].startswith("_scalar_wrap:"):
                _ensure_scalar_wrap(spec["op"])
            node = _Node(spec["op"], spec["name"], params)
        # restore saved user attrs verbatim — never the load-time
        # ambient AttrScope
        node.attrs = dict(spec.get("user_attrs", {}))
        node.inputs = [(nodes[i], oi) for i, oi, *_ in spec["inputs"]]
        nodes.append(node)
    heads = [(nodes[i], oi) for i, oi, *_ in payload["heads"]]
    return Symbol(heads)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# mutable-input positions per op (parity: FMutateInputs registrations)
_AUX_INPUT_POS = {"BatchNorm": (3, 4), "batch_norm": (3, 4),
                  "SyncBatchNorm": (3, 4)}


def _param_shape_rule(op_name, pos, data_shape, params):
    """Derive a parameter input's shape from the op's data shape +
    static params (parity: the FInferShape of each reference op filling
    unknown weight dims; mirrors gluon deferred-init rules)."""
    if data_shape is None:
        return None
    p = params

    def _prod(xs):
        out = 1
        for x in xs:
            out *= x
        return out

    if op_name == "FullyConnected":
        nh = p.get("num_hidden")
        flatten = p.get("flatten", True)
        if pos == 1:
            return (nh, _prod(data_shape[1:]) if flatten
                    else data_shape[-1])
        if pos == 2:
            return (nh,)
    elif op_name == "Convolution":
        nf = p.get("num_filter")
        k = tuple(p.get("kernel", ()))
        g = p.get("num_group", 1)
        layout = p.get("layout") or "NCHW"
        c_last = layout.endswith("C")
        cin = data_shape[-1] if c_last else data_shape[1]
        if pos == 1:
            # weight layout follows _conv_dnums: OI+spatial for NC-first,
            # O+spatial+I for C-last
            return ((nf,) + k + (cin // g,)) if c_last \
                else ((nf, cin // g) + k)
        if pos == 2:
            return (nf,)
    elif op_name == "Deconvolution":
        nf = p.get("num_filter")
        k = tuple(p.get("kernel", ()))
        g = p.get("num_group", 1)
        cin = data_shape[1]
        if pos == 1:
            return (cin, nf // g) + k
        if pos == 2:
            return (nf,)
    elif op_name in ("BatchNorm", "InstanceNorm"):
        c = data_shape[p.get("axis", 1)]
        return (c,)
    elif op_name == "LayerNorm":
        c = data_shape[p.get("axis", -1)]
        return (c,)
    elif op_name == "Embedding":
        if pos == 1:
            return (p.get("input_dim"), p.get("output_dim"))
    elif op_name in ("SoftmaxOutput", "softmax_output",
                     "LinearRegressionOutput", "LogisticRegressionOutput",
                     "MAERegressionOutput"):
        if pos == 1:    # label: batch-shaped (class dim dropped for
            # SoftmaxOutput, parity: softmax_output.cc FInferShape)
            return (data_shape[0],) if op_name.startswith("Softmax") \
                else tuple(data_shape)
    return None
