"""Device model.

TPU-native re-expression of the reference's ``Context``
(``include/mxnet/base.h:90-116``): a (device_type, device_id) pair plus a
thread-local "current context" stack.  Device types are ``cpu`` and ``tpu``
(``gpu`` is accepted as an alias for the accelerator so reference-era user
code keeps working).  A Context resolves to a concrete ``jax.Device``.
"""
from __future__ import annotations

import threading
from typing import List, Optional

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "tpu", "gpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus", "default_device"]

_thread_local = threading.local()


class Context:
    """A device context. ``Context('tpu', 0)`` or ``Context('cpu')``.

    Parity: ``Context`` in include/mxnet/base.h:90.  ``kCPUPinned`` /
    ``kCPUShared`` collapse into plain ``cpu`` — host staging and shared
    memory are handled by jax/XLA transfer machinery.
    """

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "tpu"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3}

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            device_type, device_id = device_type.device_type, device_type.device_id
        if device_type not in self.devstr2type:
            raise MXNetError(f"unknown device type {device_type!r}")
        # canonicalize gpu → tpu (accelerator)
        self.device_type = "tpu" if device_type == "gpu" else device_type
        self.device_id = int(device_id)

    # -- identity ----------------------------------------------------------
    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- resolution --------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        """Concrete jax.Device backing this context."""
        kind = "cpu" if self.device_type.startswith("cpu") else None
        if kind == "cpu":
            devs = ([d for d in jax.local_devices() if d.platform == "cpu"]
                    if _has_platform("cpu") else jax.local_devices())
        else:
            devs = _accelerator_devices()
        if not devs:
            raise MXNetError(f"no devices for context {self}")
        if self.device_id >= len(devs):
            raise MXNetError(f"device_id {self.device_id} out of range for {self.device_type} "
                             f"({len(devs)} visible)")
        return devs[self.device_id]

    @classmethod
    def from_string(cls, s: str) -> "Context":
        """Parse 'tpu(0)' / 'cpu' / 'gpu(1)' (parity: Context::FromString)."""
        s = s.strip()
        if "(" in s:
            name, rest = s.split("(", 1)
            return cls(name.strip(), int(rest.rstrip(")")))
        return cls(s)

    # -- context stack -----------------------------------------------------
    def __enter__(self):
        stack = _ctx_stack()
        stack.append(self)
        return self

    def __exit__(self, *exc):
        _ctx_stack().pop()
        return False


def _has_platform(name: str) -> bool:
    try:
        jax.devices(name)
        return True
    except RuntimeError:
        return False


def _accelerator_devices():
    """Process-local non-CPU devices; falls back to CPU when running
    host-only tests.  Local (addressable) devices only — in multi-process
    jax, global devices cannot receive device_put."""
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return devs if devs else jax.local_devices()


def _ctx_stack() -> List[Context]:
    if not hasattr(_thread_local, "stack"):
        _thread_local.stack = []
    return _thread_local.stack


def current_context() -> Context:
    """Innermost ``with ctx:`` context, else the process default device."""
    stack = _ctx_stack()
    if stack:
        return stack[-1]
    return default_device()


_default: Optional[Context] = None


def default_device() -> Context:
    """Default context: the first accelerator if present, else cpu."""
    global _default
    if _default is None:
        dev = jax.local_devices()[0]
        _default = Context("cpu" if dev.platform == "cpu" else "tpu", 0)
    return _default


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for the accelerator context (reference-compat: mx.gpu())."""
    return Context("tpu", device_id)


def num_tpus() -> int:
    devs = [d for d in jax.local_devices() if d.platform != "cpu"]
    return len(devs)


def num_gpus() -> int:
    """Reference-compat alias (mx.context.num_gpus)."""
    return num_tpus()
