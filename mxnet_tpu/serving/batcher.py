"""DynamicBatcher: coalesce concurrent requests into bucketed dispatches.

The robustness surface of the serving subsystem sits here, in front of
the engine:

- **admission validation** — every request is shape/dtype-checked
  (``InferenceEngine.validate``) BEFORE it enters the queue, so one
  malformed request can never poison a coalesced batch;
- **bounded queue** — at ``queue_depth`` pending requests, new arrivals
  are rejected with :class:`QueueFullError` (shed load instead of
  buffering toward OOM);
- **per-request deadlines** — a request whose deadline passes while
  queued is expired with :class:`RequestTimeoutError` instead of being
  dispatched late;
- **graceful drain** — ``close(drain=True)`` stops admission, then
  delivers every already-admitted response before returning.

One dispatcher thread pops the queue, waits up to ``max_delay_ms`` for
the batch to fill toward ``max_batch_size``, groups concatenable
requests (same padded example shape/dtype), and hands each group to the
engine as ONE padded batch — results scatter back to the per-request
futures.  Every dispatch emits a telemetry step record (source
``serving.DynamicBatcher``) carrying batch occupancy, padding waste and
per-request latency, reconciled by ``tools/telemetry_report.py``.

Every admitted request also carries a monotonic request id (slo.py):
stamped into its ``serving.enqueue`` span, its cross-thread
``serving.request`` lifecycle span (begun at admission, ended at
dispatch/expiry with the validate / queue-wait / hold / dispatch /
pad-share decomposition), and the ``request_ids`` list on the
``serving.coalesce`` / ``serving.dispatch`` spans and the step record —
so one slow request is joinable across every serving layer.  When SLO
objectives are declared (``slo.declare()`` / ``MXNET_SLO_LATENCY_MS``)
each finished request feeds the burn-rate evaluator inline.

Tests drive the batcher deterministically with ``start=False`` +
``flush()`` (no thread, no sleeps); the server runs it threaded.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .. import telemetry
from .. import tracing
from ..base import getenv_int
from . import slo
from .engine import (InferenceEngine, QueueFullError, RequestTimeoutError,
                     ServingClosedError)

__all__ = ["DynamicBatcher"]


def _getenv_float(name: str, default: float) -> float:
    import os
    v = os.environ.get(name)
    if v is None:
        return default
    try:
        return float(v)
    except ValueError:
        return default


class _Future:
    """Minimal thread-safe future (stdlib concurrent.futures carries an
    executor surface this queue doesn't need)."""

    __slots__ = ("_event", "_result", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc = None

    def set_result(self, result):
        self._result = result
        self._event.set()

    def set_exception(self, exc):
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise RequestTimeoutError(
                f"no response within {timeout:.3f}s")
        if self._exc is not None:
            raise self._exc
        return self._result


class _Pending:
    __slots__ = ("example", "future", "deadline", "t_submit", "group",
                 "rid", "validate_ms", "t_taken", "span")

    def __init__(self, example, group, deadline, rid, validate_ms):
        self.example = example
        self.future = _Future()
        self.deadline = deadline
        self.t_submit = time.perf_counter()
        self.group = group
        self.rid = rid
        self.validate_ms = validate_ms
        self.t_taken = None      # stamped when popped into a batch
        self.span = None         # open serving.request span (tracing on)


class DynamicBatcher:
    """Coalesce concurrent single-example requests into padded batches.

    Knobs (constructor arg > env var > default):

    - ``max_batch_size`` / ``MXNET_SERVING_MAX_BATCH`` (32): most
      requests coalesced into one dispatch.
    - ``max_delay_ms`` / ``MXNET_SERVING_MAX_DELAY_MS`` (2.0): how long
      the dispatcher holds the first request of a batch waiting for the
      batch to fill.  0 dispatches whatever one queue sweep finds.
    - ``queue_depth`` / ``MXNET_SERVING_QUEUE_DEPTH`` (256): pending
      requests admitted before shedding load.
    - ``timeout_ms``: default per-request deadline (None = no deadline).
    """

    def __init__(self, engine: InferenceEngine,
                 max_batch_size: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 start: bool = True):
        self.engine = engine
        self.max_batch_size = max(1, max_batch_size if max_batch_size
                                  is not None else
                                  getenv_int("MXNET_SERVING_MAX_BATCH", 32))
        self.max_delay_ms = max(0.0, max_delay_ms if max_delay_ms
                                is not None else
                                _getenv_float("MXNET_SERVING_MAX_DELAY_MS",
                                              2.0))
        self.queue_depth = max(1, queue_depth if queue_depth is not None
                               else getenv_int("MXNET_SERVING_QUEUE_DEPTH",
                                               256))
        self.timeout_ms = timeout_ms
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._gauge = telemetry.gauge("serving.queue_depth")
        self._gauge.set(0)
        # last-emitted cumulative reject/timeout counts, so each step
        # record carries deltas the report tool can sum; baselined at
        # construction or the first record would claim every reject the
        # process (an earlier batcher) ever counted
        self._emitted = {
            "rejects": telemetry.counter("serving.rejected.queue_full").value
            + telemetry.counter("serving.rejected.shape").value,
            "timeouts": telemetry.counter("serving.timeouts").value,
        }
        slo.note_batcher(self)   # queue_saturation remediation target
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-serving-batcher", daemon=True)
            self._thread.start()
        return self

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0):
        """Stop admission; with ``drain`` deliver every admitted
        response before returning, else fail pending futures with
        :class:`ServingClosedError`."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            if not drain:
                while self._q:
                    p = self._q.popleft()
                    tracing.end(p.span, error="ServingClosedError")
                    p.future.set_exception(
                        ServingClosedError("server shut down before "
                                           "this request was dispatched"))
            self._gauge.set(len(self._q))
            self._cv.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout)
        # no thread (start=False) or a wedged one: drain inline
        if drain:
            self.flush()

    @property
    def closed(self) -> bool:
        return self._closed

    def pending(self) -> int:
        with self._cv:
            return len(self._q)

    # -- admission ----------------------------------------------------------

    def submit(self, x, timeout_ms: Optional[float] = None) -> _Future:
        """Admit one request; returns a future resolving to the
        per-example result.  Raises BadRequestError (shape/dtype),
        QueueFullError (depth), ServingClosedError (draining) — all
        BEFORE the request can touch a batch."""
        # validation happens outside the lock (numpy work), and before
        # admission: a request that raises here was never queued
        _t0 = time.perf_counter()
        example = self.engine.validate(x)
        example, _ = self.engine.pad_example(example)
        group = self.engine.group_key(example)
        validate_ms = round((time.perf_counter() - _t0) * 1e3, 3)
        ms = timeout_ms if timeout_ms is not None else self.timeout_ms
        deadline = (time.perf_counter() + ms / 1e3
                    if ms is not None else None)
        rid = slo.next_request_id()
        with self._cv:
            # expire overdue neighbours on the submitter's clock too —
            # a deadline that lapsed behind a long dispatch shouldn't
            # wait for the dispatcher to wake up to resolve
            self._expire(time.perf_counter())
            if self._closed:
                raise ServingClosedError("server is draining/closed")
            if len(self._q) >= self.queue_depth:
                telemetry.counter("serving.rejected.queue_full").inc()
                raise QueueFullError(
                    f"queue at depth {self.queue_depth}; load shed")
            p = _Pending(example, group, deadline, rid, validate_ms)
            # cross-thread request lifecycle span: begun here (after
            # admission — rejects never open one), ended at dispatch or
            # expiry with the full latency decomposition
            p.span = tracing.begin("serving.request", request_id=rid)
            self._q.append(p)
            depth = len(self._q)
            self._gauge.set(depth)
            self._cv.notify()
        tracing.record_span("serving.enqueue", _t0, time.perf_counter(),
                            queue_depth=depth, request_id=rid)
        return p.future

    # -- dispatch -----------------------------------------------------------

    def _fail_expired(self, pend, now: float) -> list:
        """Fail every request in ``pend`` whose deadline passed and
        return the survivors (order preserved)."""
        live = []
        for p in pend:
            if p.deadline is not None and now > p.deadline:
                telemetry.counter("serving.timeouts").inc()
                tracing.end(p.span, error="RequestTimeoutError")
                if slo.active():
                    lat = round((now - p.t_submit) * 1e3, 3)
                    slo.observe_request({
                        "id": p.rid, "ok": False,
                        "error": "RequestTimeoutError",
                        "latency_ms": lat, "queue_ms": lat,
                        "validate_ms": p.validate_ms,
                        "ts": round(time.time(), 3)})
                p.future.set_exception(RequestTimeoutError(
                    "request expired in queue before dispatch"))
            else:
                live.append(p)
        return live

    def _expire(self, now: float) -> None:
        """Expire queued requests whose deadline passed (caller holds
        the lock)."""
        live = self._fail_expired(self._q, now)
        if len(live) != len(self._q):
            self._q.clear()
            self._q.extend(live)
            self._gauge.set(len(self._q))

    def _nearest_deadline(self, batch) -> Optional[float]:
        """Earliest deadline across a held batch and the queue (caller
        holds the lock) — bounds hold-loop waits so expiry is prompt."""
        dl = [p.deadline for p in batch if p.deadline is not None]
        dl += [p.deadline for p in self._q if p.deadline is not None]
        return min(dl) if dl else None

    def _take_group(self) -> List[_Pending]:
        """Pop up to ``max_batch_size`` requests sharing the head
        request's group key (caller holds the lock)."""
        _t0 = time.perf_counter()
        self._expire(_t0)
        if not self._q:
            return []
        head = self._q[0].group
        batch, keep = [], deque()
        while self._q:
            p = self._q.popleft()
            if p.group == head and len(batch) < self.max_batch_size:
                p.t_taken = time.perf_counter()
                batch.append(p)
            else:
                keep.append(p)
        self._q.extend(keep)
        self._gauge.set(len(self._q))
        tracing.record_span("serving.coalesce", _t0, time.perf_counter(),
                            batch_size=len(batch),
                            request_ids=[p.rid for p in batch])
        return batch

    def _loop(self):
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    # expire on every idle wakeup too: with an empty
                    # queue nothing can lapse, but a request admitted
                    # and lapsed between wakeups must not wait for the
                    # next coalesce to resolve
                    self._cv.wait(0.1)
                    self._expire(time.perf_counter())
                if not self._q and self._closed:
                    return
                batch = self._take_group()
                if batch and len(batch) < self.max_batch_size \
                        and self.max_delay_ms > 0 and not self._closed:
                    # hold the batch open for stragglers — but keep
                    # expiring: a deadline that lapses inside the hold
                    # window (queued OR already held) resolves now, not
                    # after the window closes
                    t_end = time.perf_counter() + self.max_delay_ms / 1e3
                    while len(batch) < self.max_batch_size:
                        now = time.perf_counter()
                        self._expire(now)
                        batch = self._fail_expired(batch, now)
                        if not batch:
                            break
                        left = t_end - now
                        if left <= 0:
                            break
                        dl = self._nearest_deadline(batch)
                        if dl is not None:
                            left = min(left, max(dl - now, 1e-4))
                        self._cv.wait(left)
                        head = batch[0].group
                        keep = deque()
                        while self._q and len(batch) < self.max_batch_size:
                            p = self._q.popleft()
                            if p.group == head:
                                p.t_taken = time.perf_counter()
                                batch.append(p)
                            else:
                                keep.append(p)
                        self._q.extend(keep)
                        self._gauge.set(len(self._q))
                        if self._closed:
                            break
                    now = time.perf_counter()
                    batch = self._fail_expired(batch, now)
                    self._expire(now)
            if batch:
                self._dispatch(batch)

    def flush(self):
        """Synchronously dispatch everything currently queued (no delay
        window) — the deterministic path tests and drain use."""
        while True:
            with self._cv:
                batch = self._take_group()
            if not batch:
                return
            self._dispatch(batch)

    def _dispatch(self, batch: List[_Pending]) -> None:
        token = telemetry.begin_step()
        t_dispatch = time.perf_counter()
        rids = [p.rid for p in batch]
        _sp = tracing.span("serving.dispatch", batch_size=len(batch),
                           request_ids=rids)
        try:
            with _sp:
                results, meta = self.engine.infer_batch(
                    [p.example for p in batch])
                _sp.annotate(padded=meta["padded"], bucket=meta["bucket"],
                             compiled=meta["compiled"])
        except Exception as e:   # a failed dispatch fails ITS batch only
            now = time.perf_counter()
            slo_on = slo.active()
            for p in batch:
                tracing.end(p.span, error=type(e).__name__)
                if slo_on:
                    lat = round((now - p.t_submit) * 1e3, 3)
                    slo.observe_request({
                        "id": p.rid, "ok": False,
                        "error": type(e).__name__, "latency_ms": lat,
                        "queue_ms": round(
                            ((p.t_taken or t_dispatch) - p.t_submit)
                            * 1e3, 3),
                        "dispatch_ms": round((now - t_dispatch) * 1e3, 3),
                        "validate_ms": p.validate_ms,
                        "batch_size": len(batch),
                        "ts": round(time.time(), 3)})
                p.future.set_exception(e)
            telemetry.counter("serving.failed_batches").inc()
            telemetry.end_step(token, "serving.DynamicBatcher",
                               extra={"serving": {"error": str(e),
                                                  "batch_size": len(batch),
                                                  "request_ids": rids}})
            return
        now = time.perf_counter()
        dispatch_ms = round((now - t_dispatch) * 1e3, 3)
        pad_share = (round(1 - len(batch) / meta["padded"], 4)
                     if meta["padded"] else 0.0)
        compile_ms = float(meta.get("compile_ms") or 0.0)
        slo_on = slo.active()
        latencies = []
        ts_wall = round(time.time(), 3)
        for p, r in zip(batch, results):
            p.future.set_result(r)
            lat = round((now - p.t_submit) * 1e3, 3)
            latencies.append(lat)
            # per-request latency decomposition: queue wait
            # (submit→taken), hold window (taken→dispatch start),
            # dispatch, validate, pad-waste share — the enqueue→reply
            # lifecycle span carries it so /tracez, /requestz and the
            # report tool can separate waiting from compute
            t_taken = p.t_taken if p.t_taken is not None else t_dispatch
            queue_ms = round((t_taken - p.t_submit) * 1e3, 3)
            hold_ms = round(max(0.0, t_dispatch - t_taken) * 1e3, 3)
            tracing.end(p.span,
                        queue_wait_ms=round(
                            (t_dispatch - p.t_submit) * 1e3, 3),
                        hold_ms=hold_ms, dispatch_ms=dispatch_ms,
                        validate_ms=p.validate_ms, pad_share=pad_share,
                        batch_size=len(batch))
            if slo_on:
                slo.observe_request({
                    "id": p.rid, "ok": True, "latency_ms": lat,
                    "validate_ms": p.validate_ms, "queue_ms": queue_ms,
                    "hold_ms": hold_ms, "dispatch_ms": dispatch_ms,
                    "pad_share": pad_share,
                    "compile_ms": round(compile_ms / len(batch), 3),
                    "bucket": meta["bucket"], "batch_size": len(batch),
                    "ts": ts_wall})
        telemetry.record_serving_batch(len(batch), meta["padded"],
                                       latencies,
                                       eager=not meta["compiled"])
        rejects = (telemetry.counter("serving.rejected.queue_full").value
                   + telemetry.counter("serving.rejected.shape").value)
        timeouts = telemetry.counter("serving.timeouts").value
        extra: Dict[str, Any] = {"serving": {
            "batch_size": len(batch),
            "padded_batch": meta["padded"],
            "bucket": meta["bucket"],
            "compiled": meta["compiled"],
            "padding_waste": pad_share,
            "queue_depth": self.pending(),
            "request_ms": latencies,
            "request_ids": rids,
            "rejects": rejects - self._emitted["rejects"],
            "timeouts": timeouts - self._emitted["timeouts"],
        }}
        self._emitted["rejects"] = rejects
        self._emitted["timeouts"] = timeouts
        telemetry.end_step(token, "serving.DynamicBatcher", extra=extra)
