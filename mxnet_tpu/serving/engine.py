"""InferenceEngine: shape-bucketed AOT-compiled serving forward.

The serving half of the compiled-graph machinery the training funnels
already use (``HybridBlock._call_cached``, ``imperative/cached_step``):
one ahead-of-time compiled, donation-free inference executable per
**shape bucket**, amortized across every request whose padded shape
lands in that bucket.  Batch (and optional sequence) dims are padded up
to configurable powers-of-two, so a steady request mix touches a small,
bounded set of executables instead of one compile per arriving shape.

Compile-storm behavior is shared with the op funnel: fresh buckets burn
the same ``MXNET_JIT_MAX_SIGS`` budget (``ops.registry.SigBudget``);
over budget the engine latches — new shapes run eager, every
already-compiled bucket keeps serving its executable, nothing is
evicted.  Blocks carrying forward hooks are never compiled (hooks
observe real activations), and ``MXNET_SERVING=0`` forces the eager
path process-wide; both fallbacks serve identical numerics.

Requests are single examples (no batch axis).  Results come back as
host numpy arrays: the dispatch path performs exactly ONE XLA
executable dispatch per coalesced batch (asserted in tier-1 via the
unified ``dispatch.count`` counter) — scatter/slicing happens host-side
so per-request result delivery costs no extra device dispatches.
"""
from __future__ import annotations

import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as onp
import jax
import jax.numpy as jnp

from .. import autograd as ag
from .. import profiler, telemetry, tracing
from ..base import MXNetError, getenv
from ..log import get_logger
from ..gluon.block import (_ExportedBlock, _TraceContext, _trace_scope,
                           _walk_blocks)
from ..ndarray import NDArray
from ..ops import random as _rng
from ..ops.registry import SigBudget, apply_jax

__all__ = ["InferenceEngine", "BadRequestError", "QueueFullError",
           "RequestTimeoutError", "ServingClosedError", "serving_enabled"]


class BadRequestError(MXNetError):
    """Request rejected at admission: shape/dtype/rank incompatible with
    the engine's example spec.  Raised BEFORE the request enters the
    queue, so one malformed request can never poison a batch."""


class QueueFullError(MXNetError):
    """Request shed at admission: the bounded queue is at depth (load is
    shed instead of buffering toward OOM)."""


class RequestTimeoutError(MXNetError):
    """Request expired before dispatch (per-request deadline passed)."""


class ServingClosedError(MXNetError):
    """Request arrived after shutdown/drain began."""


def serving_enabled() -> bool:
    """MXNET_SERVING=0 disables the compiled bucket path (every batch
    runs eager).  Read per dispatch, so it can be flipped live."""
    return (getenv("MXNET_SERVING", "1") or "1").lower() \
        not in ("0", "false", "off")


def _next_pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length()


class InferenceEngine:
    """AOT-compiled, shape-bucketed inference over any Block.

    Parameters
    ----------
    block : Block | HybridBlock | _ExportedBlock
        The model.  For an ``_ExportedBlock`` (from ``HybridBlock.export``
        → ``SymbolBlock.imports``) the buckets are the exported input
        signatures — serialized StableHLO is already AOT, so the engine
        only pads/routes.
    example_shape : tuple, optional
        Per-example input shape (no batch axis).  ``None`` entries mark
        variable axes (bucketed when listed in ``seq_axes``).  Learned
        from the first request when omitted.
    dtype : str, optional
        Expected input dtype (learned from the first request when
        omitted).
    bucket_sizes : sequence of int, optional
        Explicit allowed batch-bucket sizes (sorted ascending); default
        is unbounded powers of two.
    seq_axes : sequence of int
        Example axes (0-based, batch excluded) padded up to power-of-two
        buckets, for variable-length inputs.  Padding is zeros; only use
        for models whose per-row outputs ignore trailing positions.
    max_sigs : int, optional
        Compiled-bucket budget; defaults to ``MXNET_JIT_MAX_SIGS``.
    """

    def __init__(self, block, example_shape: Optional[Sequence] = None,
                 dtype: Optional[str] = None,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 seq_axes: Sequence[int] = (),
                 max_sigs: Optional[int] = None,
                 name: Optional[str] = None):
        self._block = block
        self._name = name or type(block).__name__
        self._exported = isinstance(block, _ExportedBlock)
        self._example_shape = (tuple(example_shape)
                               if example_shape is not None else None)
        self._dtype = str(dtype) if dtype is not None else None
        self._bucket_sizes = (sorted(int(b) for b in bucket_sizes)
                              if bucket_sizes else None)
        self._seq_axes = tuple(int(a) for a in seq_axes)
        self._budget = SigBudget(max_sigs)
        # bucket key -> (runner, treedef) | None (bucket latched eager
        # after a failed compile)
        self._compiled: Dict[Any, Any] = {}
        self._lock = threading.Lock()
        self._init_done = False
        self._embedding = None
        if self._exported:
            self._adopt_exported_spec()

    # -- spec / admission ---------------------------------------------------

    def _adopt_exported_spec(self):
        sigs = self._block.input_signatures()
        if not sigs:
            raise MXNetError("exported block carries no input signatures")
        if any(len(s) != 1 for s in sigs):
            raise MXNetError(
                "serving supports single-input exported blocks; got "
                f"signatures {sigs}")
        shapes = [s[0][0] for s in sigs]
        dtypes = {s[0][1] for s in sigs}
        if len(dtypes) != 1:
            raise MXNetError(
                f"exported signatures disagree on dtype: {dtypes}")
        self._dtype = dtypes.pop()
        trailing = {tuple(sh[1:]) for sh in shapes}
        if len(trailing) != 1:
            raise MXNetError(
                f"exported signatures disagree on example shape: {shapes}")
        self._example_shape = trailing.pop()
        # exported artifacts can only serve the batch sizes they were
        # exported with — those ARE the buckets
        self._bucket_sizes = sorted({int(sh[0]) for sh in shapes})

    @property
    def example_shape(self):
        return self._example_shape

    @property
    def dtype(self):
        return self._dtype

    def attach_embedding(self, lookup) -> None:
        """Attach an embedding lookup tier (an
        ``embedding.EmbeddingLookupCache`` or anything with
        ``lookup(ids) -> (n, dim)`` and ``dim``): integer-dtype
        requests are treated as row ids and translated to dense
        embedding features AT ADMISSION, so inference batches consult
        the LRU tier instead of the parameter server (repeated users
        hit the cache; only cold rows travel on the sparse pull wire)
        and the compiled shape buckets always see float batches."""
        self._embedding = lookup

    def _embed_request(self, arr: onp.ndarray) -> onp.ndarray:
        """ids ``(...,)`` -> features ``(..., dim)`` through the
        attached lookup tier; malformed ids surface as admission
        rejects like any other bad request."""
        try:
            vecs = self._embedding.lookup(arr.reshape(-1))
        except Exception as e:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"embedding lookup rejected request ids: {e}") from None
        return vecs.reshape(tuple(arr.shape) + (vecs.shape[-1],))

    def validate(self, x) -> onp.ndarray:
        """Admission gate: normalize one request to a host numpy example
        and check it against the engine spec.  Raises
        :class:`BadRequestError` (and ticks ``serving.rejected.shape``)
        on any mismatch — malformed requests never reach a batch.  With
        an embedding lookup tier attached, integer requests are row ids
        and are translated to dense features here, BEFORE the spec
        check (the engine spec describes the embedded batch)."""
        try:
            arr = onp.asarray(x.asnumpy() if isinstance(x, NDArray) else x)
        except Exception as e:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(f"request is not array-like: {e}") from None
        if self._embedding is not None and \
                onp.issubdtype(arr.dtype, onp.integer):
            arr = self._embed_request(arr)
        if self._dtype is None:
            if not (onp.issubdtype(arr.dtype, onp.floating)
                    or onp.issubdtype(arr.dtype, onp.integer)
                    or arr.dtype == onp.bool_):
                telemetry.counter("serving.rejected.shape").inc()
                raise BadRequestError(
                    f"request dtype {arr.dtype} is not numeric")
            self._dtype = str(arr.dtype)
        elif str(arr.dtype) != self._dtype:
            try:
                cast = arr.astype(self._dtype)
            except (TypeError, ValueError):
                cast = None
            if cast is None or not onp.array_equal(
                    cast.astype(arr.dtype, copy=False), arr):
                telemetry.counter("serving.rejected.shape").inc()
                raise BadRequestError(
                    f"request dtype {arr.dtype} does not match engine "
                    f"dtype {self._dtype}")
            arr = cast
        if arr.size == 0:
            # a zero-size example would poison its whole coalesced
            # batch with a degenerate bucket (and a zero-length prompt
            # has no last position to decode from)
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"request shape {arr.shape} has a zero-size axis")
        if self._example_shape is None:
            self._example_shape = tuple(
                None if i in self._seq_axes else d
                for i, d in enumerate(arr.shape))
        spec = self._example_shape
        if arr.ndim != len(spec):
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"request rank {arr.ndim} (shape {arr.shape}) does not "
                f"match example spec {spec}")
        for i, (have, want) in enumerate(zip(arr.shape, spec)):
            if want is not None and have != want:
                telemetry.counter("serving.rejected.shape").inc()
                raise BadRequestError(
                    f"request shape {arr.shape} does not match example "
                    f"spec {spec} (axis {i}: {have} != {want})")
        return arr

    def _bucket_batch(self, n: int) -> int:
        if n <= 0:
            raise BadRequestError(
                f"batch size must be positive, got {n}")
        if self._bucket_sizes is not None:
            for b in self._bucket_sizes:
                if b >= n:
                    return b
            raise BadRequestError(
                f"batch of {n} exceeds the largest available bucket "
                f"{self._bucket_sizes[-1]} (exported artifacts serve "
                "only their exported batch sizes)")
        return _next_pow2(n)

    def pad_example(self, arr: onp.ndarray) -> Tuple[onp.ndarray,
                                                     Tuple[int, ...]]:
        """Pad an admitted example's seq axes up to their buckets.
        Returns (padded example, original shape)."""
        orig = arr.shape
        if not self._seq_axes:
            return arr, orig
        pads = []
        for i, d in enumerate(arr.shape):
            want = _next_pow2(d) if i in self._seq_axes else d
            pads.append((0, want - d))
        if any(p[1] for p in pads):
            arr = onp.pad(arr, pads)
        return arr, orig

    def group_key(self, padded: onp.ndarray):
        """Coalescing key: requests sharing it are concatenable."""
        return (padded.shape, str(padded.dtype))

    # -- compile ------------------------------------------------------------

    def _bucket_tag(self, key) -> str:
        (shape, dtype) = key
        return "x".join(str(d) for d in shape) + ":" + dtype

    def _ensure_init(self, batched: onp.ndarray):
        """Finish deferred parameter init with one eager forward (the
        analogue of HybridBlock's first-call eager pass)."""
        if self._init_done or self._exported:
            return
        params = self._block.collect_params()
        if any(p._deferred_init is not None for p in params.values()):
            with ag.pause(train_mode=False):
                self._block(NDArray(jnp.asarray(batched)))
        for p in params.values():
            p._check_initialized()
        self._init_done = True

    def _artifact_sig(self, key):
        """Content signature of one bucket executable for the artifact
        store: the bucket key plus everything the traced forward bakes
        in that the store's own key material doesn't already carry —
        model identity (name/class), the parameter spec in call order,
        and the engine's padding config.  Stable across processes for
        the same model construction."""
        params = self._block.collect_params()
        return (self._name, type(self._block).__name__,
                tuple((k, tuple(p.data().shape), str(p.data().dtype))
                      for k, p in params.items()),
                key, tuple(self._seq_axes))

    def _compile(self, key, batched_shape, dtype):
        """Trace + AOT-compile the inference forward for one bucket:
        a pure function of (rng key, *params, input) lowered and
        compiled ahead of execution (donation-free — serving never owns
        its inputs).  Consults the executable-artifact store first — a
        warm replica deserializes the bucket (zero compiles, output
        treedef restored from the artifact metadata since no trace
        runs) — and commits every fresh compile back.  Returns the
        cache entry, or None when this bucket latched eager
        (trace/compile failure)."""
        from .. import artifacts
        block = self._block
        params = block.collect_params()
        pvals = list(params.values())
        cell: Dict[str, Any] = {"n_out": None, "treedef": None}
        asig = self._artifact_sig(key)
        art = artifacts.load("serving_bucket", asig)
        if art is not None:
            # warm replica: the executable deserializes instead of
            # compiling — no trace runs, so the output structure comes
            # from the artifact's metadata, and neither record_compile
            # nor the bucket compile counter ticks (compiles stays 0)
            cell["n_out"] = art.meta["n_out"]
            cell["treedef"] = art.meta["treedef"]
            return self._make_runner(art.compiled, params, pvals), cell

        def traced(rkey, *arrays):
            p_arr = arrays[:len(pvals)]
            in_arr = arrays[len(pvals):]
            tc = _TraceContext(rkey)
            saved = [p._data for p in pvals]
            try:
                for p, a in zip(pvals, p_arr):
                    p._data = NDArray(a)
                with _trace_scope(tc), ag.pause(train_mode=False):
                    out = block(NDArray(in_arr[0]))
                leaves, treedef = jax.tree_util.tree_flatten(
                    out, is_leaf=lambda x: isinstance(x, NDArray))
                raw = [l._data if isinstance(l, NDArray) else jnp.asarray(l)
                       for l in leaves]
                cell["n_out"] = len(raw)
                cell["treedef"] = treedef
                # inference never applies aux updates (running stats are
                # read, not written, outside train_mode)
                return tuple(raw)
            finally:
                for p, s in zip(pvals, saved):
                    p._data = s

        # current_key(): only the key's shape/dtype matter for the spec,
        # and peeking keeps the host PRNG stream identical whether this
        # bucket compiled fresh or deserialized from the artifact store
        rkey = _rng.current_key()
        specs = [jax.ShapeDtypeStruct(rkey.shape, rkey.dtype)]
        specs += [jax.ShapeDtypeStruct(p.data().shape,
                                       jnp.dtype(str(p.data().dtype)))
                  for p in pvals]
        specs += [jax.ShapeDtypeStruct(batched_shape, jnp.dtype(dtype))]
        # hybridized children would nest their own jit inside this trace;
        # suspend hybridization so the bucket lowers to ONE flat program
        hybrid = [(b, b._active) for b in
                  {id(b): b for b in _walk_blocks(block)}.values()
                  if hasattr(b, "_active")]
        t0 = _time.perf_counter()
        _sp = tracing.span("compile.serving",
                           bucket=self._bucket_tag(key))
        try:
            with _sp:
                for b, _ in hybrid:
                    b._active = False
                compiled = jax.jit(traced).lower(*specs).compile()
        except Exception:
            return None
        finally:
            for b, was in hybrid:
                b._active = was
        telemetry.record_compile(_time.perf_counter() - t0, "serving")
        telemetry.counter(
            f"serving.bucket.{self._bucket_tag(key)}.compiles").inc()
        artifacts.save("serving_bucket", asig, compiled,
                       meta={"n_out": cell["n_out"],
                             "treedef": cell["treedef"],
                             "bucket": self._bucket_tag(key)})
        return self._make_runner(compiled, params, pvals), cell

    @staticmethod
    def _make_runner(compiled, params, pvals):
        """Dispatch closure over one bucket executable — shared by the
        fresh-compile and artifact-deserialize paths, which produce
        call-compatible executables."""
        n_params = len(pvals)

        def runner(batched_nd: NDArray):
            rkey = _rng.next_key()
            arrays = [NDArray(rkey)] + \
                [params[k].data() for k in params] + [batched_nd]
            assert len(arrays) == n_params + 2
            return apply_jax(lambda *arr: compiled(*arr), arrays,
                             multi_out=True, record=False)

        return runner

    def warmup(self, specs: Sequence) -> List[str]:
        """AOT-compile buckets ahead of traffic.  ``specs`` entries are
        batch sizes (int) — example shape/dtype must be known — or full
        batched shapes (tuple), optionally (shape, dtype).  Returns the
        bucket tags compiled (or already present)."""
        # prefetch the persistent kernel-autotune cache first: any
        # Pallas-backed op traced during bucket compilation resolves
        # its tuned config from the in-process memo instead of parsing
        # the cache file (or worse, measuring) inside a compile
        from .. import kernels
        n_kern = kernels.warm_cache()
        if n_kern:
            get_logger("mxnet_tpu.serving").info(
                "warmup: %d tuned kernel config(s) preloaded", n_kern)
        tags = []
        for spec in specs:
            dtype = self._dtype
            if isinstance(spec, (int, onp.integer)):
                if self._example_shape is None or dtype is None or \
                        any(d is None for d in self._example_shape):
                    raise MXNetError(
                        "warmup(batch_size) needs a fully-specified "
                        "example_shape and dtype at engine construction")
                shape = (self._bucket_batch(int(spec)),
                         *self._example_shape)
            else:
                if isinstance(spec, tuple) and len(spec) == 2 and \
                        not isinstance(spec[0], (int, onp.integer)):
                    shape, dtype = tuple(spec[0]), str(spec[1])
                else:
                    shape = tuple(spec)
                shape = (self._bucket_batch(shape[0]), *shape[1:])
            if dtype is None:
                raise MXNetError("warmup spec needs a dtype")
            key = (shape, str(dtype))
            self._get_runner(key, warm=True)
            tags.append(self._bucket_tag(key))
        return tags

    def _get_runner(self, key, warm: bool = False):
        """The compiled entry for a bucket key, compiling under budget;
        None when this dispatch must run eager."""
        if self._exported:
            return "exported"
        if not serving_enabled():
            return None
        if not warm and self._block.has_hooks():
            # hooks observe real activations: decline capture, run eager
            # so the hooks fire per dispatch
            return None
        # the AMP policy token joins the cache key: the traced forward
        # bakes the policy's compute-dtype casts into the bucket
        # executable (via the op funnel's bound partials), so a bucket
        # compiled fp32 must not serve traffic after an amp.init flip —
        # the fresh token minted here compiles a fresh executable
        from ..amp import policy as _amp_policy
        ckey = (key, _amp_policy.cache_token())
        entry = self._compiled.get(ckey)
        if entry is not None:
            return entry          # includes the eager latch sentinel
        with self._lock:
            entry = self._compiled.get(ckey)
            if entry is None:
                n_live = sum(1 for v in self._compiled.values()
                             if v is not None)
                if not self._budget.admit(n_live):
                    return None   # over budget: eager, no eviction
                shape, dtype = key
                self._ensure_init(onp.zeros(shape, dtype))
                entry = self._compile(key, shape, dtype)
                if entry is None:
                    entry = "eager"     # failed compile: latch this bucket
                self._compiled[ckey] = entry
        return entry if entry != "eager" else None

    # -- dispatch -----------------------------------------------------------

    def _infer_committed(self, batch):
        """Device-committed batch fast path: a pre-stacked batch the
        device-feed pipeline already placed on-device
        (``data.DevicePrefetcher`` / ``jax.device_put``) skips host
        staging entirely — no ``asnumpy`` round-trip, no re-upload.
        Batch-axis padding to the bucket happens device-side; variable
        ``seq_axes`` must arrive pre-padded (their actual length keys
        the bucket).  Dtype must match the engine spec exactly — device
        batches are never cast."""
        arr = batch._data if isinstance(batch, NDArray) else batch
        if arr.ndim == 0:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError("committed batch must carry a batch axis")
        if self._dtype is None:
            self._dtype = str(arr.dtype)
        elif str(arr.dtype) != self._dtype:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"committed batch dtype {arr.dtype} does not match "
                f"engine dtype {self._dtype}")
        if self._example_shape is None:
            self._example_shape = tuple(
                None if i in self._seq_axes else d
                for i, d in enumerate(arr.shape[1:]))
        spec = self._example_shape
        if arr.ndim - 1 != len(spec) or any(
                want is not None and have != want
                for have, want in zip(arr.shape[1:], spec)):
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"committed batch shape {arr.shape} does not match "
                f"example spec {spec}")
        n = int(arr.shape[0])
        bucket = self._bucket_batch(n)
        if bucket > n:
            arr = jnp.concatenate(
                [arr, jnp.zeros((bucket - n, *arr.shape[1:]), arr.dtype)])
        key = (tuple(arr.shape), str(arr.dtype))
        _c_ms0 = telemetry.counter("compile.serving.ms").value
        entry = self._get_runner(key)
        _compile_ms = round(
            telemetry.counter("compile.serving.ms").value - _c_ms0, 3)
        t0 = profiler.op_timer()
        batched_nd = NDArray(arr)
        if entry is not None and entry != "exported":
            runner, cell = entry
            leaves = runner(batched_nd)
            treedef = cell["treedef"]
            compiled = True
        else:
            if entry is None:
                self._ensure_init(arr)
            with ag.pause(train_mode=False):
                out = self._block(batched_nd)
            leaves, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            compiled = entry == "exported"
        profiler.op_record(f"Serving::{self._name}", t0)
        telemetry.counter(
            f"serving.bucket.{self._bucket_tag(key)}.dispatches").inc()
        telemetry.counter("serving.device_batches").inc()
        host = [l.asnumpy() if isinstance(l, NDArray) else onp.asarray(l)
                for l in leaves]
        results = []
        for i in range(n):
            rows = [h[i] if h.ndim and h.shape[0] == bucket else h
                    for h in host]
            results.append(jax.tree_util.tree_unflatten(treedef, rows)
                           if treedef is not None else rows[0])
        meta = {"bucket": self._bucket_tag(key), "padded": bucket,
                "compiled": compiled, "compile_ms": _compile_ms,
                "device_committed": True}
        return results, meta

    def infer_batch(self, examples: Sequence[onp.ndarray]):
        """Run one coalesced batch of admitted (validated, seq-padded)
        examples.  Returns ``(results, meta)``: per-example host-numpy
        results mirroring the block's output structure, and dispatch
        metadata for telemetry (bucket tag, padded size, compiled?).

        ``examples`` may also be a single pre-stacked, device-committed
        batch (``NDArray`` / ``jax.Array``, batch axis leading) — e.g.
        from a ``data.DevicePrefetcher``-fed offline scoring loop — in
        which case host staging is skipped (:meth:`_infer_committed`)."""
        if isinstance(examples, (NDArray, jax.Array)):
            return self._infer_committed(examples)
        if not examples:
            return [], {"bucket": None, "padded": 0, "compiled": False,
                        "compile_ms": 0.0}
        n = len(examples)
        stacked = onp.stack([onp.asarray(e) for e in examples])
        bucket = self._bucket_batch(n)
        if bucket > n:
            stacked = onp.pad(
                stacked, [(0, bucket - n)] + [(0, 0)] * (stacked.ndim - 1))
        key = ((bucket, *stacked.shape[1:]), str(stacked.dtype))
        # cold-compile share of this dispatch, for the per-request
        # saturation decomposition: _get_runner records any bucket
        # compile it performs into compile.serving.ms — the delta
        # across the call is THIS dispatch's compile cost
        _c_ms0 = telemetry.counter("compile.serving.ms").value
        entry = self._get_runner(key)
        _compile_ms = round(
            telemetry.counter("compile.serving.ms").value - _c_ms0, 3)
        t0 = profiler.op_timer()
        if entry == "exported":
            with ag.pause(train_mode=False):
                out = self._block(NDArray(jnp.asarray(stacked)))
            leaves, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            compiled = True
        elif entry is not None:
            runner, cell = entry
            leaves = runner(NDArray(jnp.asarray(stacked)))
            treedef = cell["treedef"]
            compiled = True
        else:
            self._ensure_init(stacked)
            with ag.pause(train_mode=False):
                out = self._block(NDArray(jnp.asarray(stacked)))
            leaves, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            compiled = False
        profiler.op_record(f"Serving::{self._name}", t0)
        telemetry.counter(
            f"serving.bucket.{self._bucket_tag(key)}.dispatches").inc()
        # host-side scatter: one transfer per output leaf, zero extra
        # device dispatches for per-request slicing
        host = [l.asnumpy() if isinstance(l, NDArray) else onp.asarray(l)
                for l in leaves]
        results = []
        for i in range(n):
            rows = [h[i] if h.ndim and h.shape[0] == bucket else h
                    for h in host]
            results.append(jax.tree_util.tree_unflatten(treedef, rows)
                           if treedef is not None else rows[0])
        meta = {"bucket": self._bucket_tag(key), "padded": bucket,
                "compiled": compiled, "compile_ms": _compile_ms}
        return results, meta

    def infer(self, x, timeout_ms=None):
        """Single-request convenience: validate → pad → dispatch a
        1-request batch.  (``timeout_ms`` accepted for API symmetry with
        the batcher; a direct call never queues.)"""
        arr = self.validate(x)
        arr, _ = self.pad_example(arr)
        results, _ = self.infer_batch([arr])
        return results[0]

    # -- introspection ------------------------------------------------------

    def buckets(self) -> List[str]:
        """Tags of the buckets currently holding a compiled executable."""
        if self._exported:
            return [self._bucket_tag(((b, *self._example_shape),
                                      self._dtype))
                    for b in self._bucket_sizes]
        return sorted(self._bucket_tag(k)
                      for (k, _tok), v in self._compiled.items()
                      if v is not None)

    def stats(self) -> Dict[str, Any]:
        out = {
            "buckets": len(self.buckets()),
            "latched": self._budget.latched,
            "budget_declines": self._budget.declines,
        }
        if self._embedding is not None and \
                hasattr(self._embedding, "stats"):
            out["embedding"] = self._embedding.stats()
        return out
