"""mxnet_tpu.serving — the inference serving subsystem.

queue → :class:`DynamicBatcher` → shape-bucketed
:class:`InferenceEngine` (AOT-compiled executable per bucket) →
per-request futures; :class:`ServingServer` fronts the pair with an
in-process ``predict()`` API and an optional stdlib HTTP JSON endpoint.
See docs/ARCHITECTURE.md (Serving) for the dataflow and the
admission/reject/timeout contract.
"""
from .engine import (InferenceEngine, BadRequestError, QueueFullError,
                     RequestTimeoutError, ServingClosedError,
                     serving_enabled)
from .batcher import DynamicBatcher
from .server import ServingServer

__all__ = ["InferenceEngine", "DynamicBatcher", "ServingServer",
           "BadRequestError", "QueueFullError", "RequestTimeoutError",
           "ServingClosedError", "serving_enabled"]
