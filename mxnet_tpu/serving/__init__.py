"""mxnet_tpu.serving — the inference serving subsystem.

queue → :class:`DynamicBatcher` → shape-bucketed
:class:`InferenceEngine` (AOT-compiled executable per bucket) →
per-request futures; :class:`ServingServer` fronts the pair with an
in-process ``predict()`` API and an optional stdlib HTTP JSON endpoint.
The ``slo`` submodule adds the SLO plane on top: request identity,
sliding-window burn-rate objectives, saturation-attributed clustermon
incidents, and the ``/slo`` + ``/requestz`` views.  The ``decode``
subpackage is the autoregressive plane: continuous batching
(:class:`DecodeScheduler`) over a paged KV cache with chunked prefill
and speculative decode, served through the same server's
``/generate``.  See docs/ARCHITECTURE.md (Serving, Serving SLOs,
Decode serving) for the dataflow and the admission/reject/timeout
contract.
"""
from . import slo
from .engine import (InferenceEngine, BadRequestError, QueueFullError,
                     RequestTimeoutError, ServingClosedError,
                     serving_enabled)
from .batcher import DynamicBatcher
from .server import ServingServer
from . import decode
from .decode import (DecodeEngine, DecodeModel, DecodeScheduler,
                     OutOfPagesError, PagedKVCache)

__all__ = ["InferenceEngine", "DynamicBatcher", "ServingServer",
           "BadRequestError", "QueueFullError", "RequestTimeoutError",
           "ServingClosedError", "serving_enabled", "slo", "decode",
           "DecodeEngine", "DecodeModel", "DecodeScheduler",
           "OutOfPagesError", "PagedKVCache"]
