"""Autoregressive decode serving: continuous batching over a paged KV
cache with optional speculative decode.

- :mod:`paged_kv` — pre-allocated device page pool + host free-list
  allocator with per-slot page tables;
- :mod:`engine` — the small causal LM + fixed-shape compiled decode /
  prefill / draft / verify executables;
- :mod:`scheduler` — the continuous batcher (``DecodeScheduler``):
  per-step admission/eviction, chunked prefill, speculative accept.

See docs/ARCHITECTURE.md "Decode serving".
"""
from .paged_kv import OutOfPagesError, PageAllocator, PagedKVCache
from .engine import DecodeEngine, DecodeModel
from .scheduler import DecodeScheduler

__all__ = ["PageAllocator", "PagedKVCache", "OutOfPagesError",
           "DecodeModel", "DecodeEngine", "DecodeScheduler"]
