"""Continuous batching for autoregressive decode.

The :class:`DecodeScheduler` is the decode-plane sibling of
``serving.DynamicBatcher``: requests are admitted into fixed decode
*slots* and evicted per engine step, not per batch.  One compiled
``decode_step`` executable covers the whole ``(max_slots,)`` grid —
the active-slot mask, per-slot positions and page tables are traced
int arrays, so admission and completion never recompile; a request
joining mid-flight costs one table row, not an XLA trace.

Each step (one turn of :meth:`step`, driven by the background thread
or manually):

1. expire — queued requests and active slots whose deadline passed
   fail with ``RequestTimeoutError``; evicted slots return their pages
   to the free list (``decode.evictions``);
2. admit — free slots pull from the queue when the page budget
   (prompt + max_new [+ spec window]) fits; pages are acquired in full
   at admission so generation can never run out mid-flight;
3. prefill — each admitted slot feeds ONE pow2-bucketed prompt chunk
   (chunked prefill: long prompts interleave with running decodes
   instead of stalling them); the final chunk yields the first token
   (TTFT);
4. decode — one batched token step over every decoding slot, either
   plain ``decode_step`` or the speculative draft→verify pair
   (``k`` proposals drafted, verified in one target dispatch,
   accepted prefix committed — greedy output is token-identical to
   the non-speculative path);
5. account — one telemetry step record (source
   ``serving.DecodeScheduler``) with the decode extras the report
   tools reconcile, plus ``serving.request`` span closure and SLO
   request feed (TTFT + latency) for finished slots.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

import numpy as onp

from ... import telemetry, tracing
from ...base import getenv_int
from .. import slo
from ..batcher import _Future, _getenv_float
from ..engine import (BadRequestError, QueueFullError,
                      RequestTimeoutError, ServingClosedError)
from .engine import DecodeEngine
from .paged_kv import OutOfPagesError

__all__ = ["DecodeScheduler"]


class _Request:
    __slots__ = ("prompt", "max_new", "eos", "future", "deadline",
                 "t_submit", "t_admit", "rid", "span", "ttft_ms",
                 "generated", "prefilled", "pending", "pos_next")

    def __init__(self, prompt, max_new, eos, deadline, rid):
        self.prompt = prompt
        self.max_new = max_new
        self.eos = eos
        self.future = _Future()
        self.deadline = deadline
        self.t_submit = time.perf_counter()
        self.t_admit = None
        self.rid = rid
        self.span = None
        self.ttft_ms = None
        self.generated: List[int] = []
        self.prefilled = 0       # prompt tokens written so far
        self.pending = None      # committed-but-unconsumed token
        self.pos_next = 0        # position the pending token occupies


class DecodeScheduler:
    """Continuous batcher over a :class:`DecodeEngine`.

    Knobs (constructor arg > env var > default): ``queue_depth`` /
    ``MXNET_SERVING_QUEUE_DEPTH`` (256), ``timeout_ms`` (default
    per-request deadline, None = none), ``max_new_tokens`` default for
    :meth:`submit` (32)."""

    def __init__(self, engine: DecodeEngine,
                 queue_depth: Optional[int] = None,
                 timeout_ms: Optional[float] = None,
                 max_new_tokens: int = 32,
                 start: bool = True):
        self.engine = engine
        self.queue_depth = max(1, queue_depth if queue_depth is not None
                               else getenv_int("MXNET_SERVING_QUEUE_DEPTH",
                                               256))
        self.timeout_ms = timeout_ms
        self.max_new_tokens = int(max_new_tokens)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._step_lock = threading.Lock()
        self._slots: List[Optional[_Request]] = [None] * engine.max_slots
        self._closed = False
        self._drain = True
        self._thread: Optional[threading.Thread] = None
        self._gauge_q = telemetry.gauge("serving.queue_depth")
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._last_compiles = engine.compiles
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-serving-decode",
                daemon=True)
            self._thread.start()
        return self

    def close(self, drain: bool = True,
              timeout: Optional[float] = 30.0) -> None:
        """Stop admission.  ``drain=True`` runs every in-flight slot
        (and queued request) to completion before returning;
        ``drain=False`` fails them all with
        :class:`ServingClosedError` and frees their pages."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            self._drain = drain
            self._cv.notify_all()
        if not drain:
            with self._step_lock:      # serialize against a live step
                with self._cv:
                    while self._q:
                        r = self._q.popleft()
                        self._finish_error(
                            r, ServingClosedError(
                                "server shut down before this request "
                                "was admitted"))
                    self._gauge_q.set(0)
                for s, r in enumerate(self._slots):
                    if r is None:
                        continue
                    self.engine.release_slot(s)
                    telemetry.counter("decode.evictions").inc()
                    self._slots[s] = None
                    self._finish_error(
                        r, ServingClosedError(
                            "server shut down mid-generation"))
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout)
        if drain:
            # no thread (manual mode) or a wedged one: drain inline
            while self._has_work():
                self.step()

    @property
    def closed(self) -> bool:
        return self._closed

    def pending(self) -> int:
        with self._cv:
            return len(self._q)

    def active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    def _has_work(self) -> bool:
        with self._cv:
            return bool(self._q) or any(
                r is not None for r in self._slots)

    # -- admission ----------------------------------------------------------

    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos: Optional[int] = None,
               timeout_ms: Optional[float] = None) -> _Future:
        """Admit one generation request; the future resolves to the
        list of generated token ids.  Raises
        :class:`BadRequestError` (empty prompt, bad token ids, page
        budget), :class:`QueueFullError`, :class:`ServingClosedError`
        — all before the request is queued."""
        if self._closed:
            raise ServingClosedError("server is draining/closed")
        max_new = (int(max_new_tokens) if max_new_tokens is not None
                   else self.max_new_tokens)
        prompt = [int(t) for t in prompt]
        vocab = self.engine.model.vocab_size
        if not prompt:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                "empty prompt: decode needs at least one token")
        if max_new < 1:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"max_new_tokens must be >= 1, got {max_new}")
        if any(t < 0 or t >= vocab for t in prompt):
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"prompt token out of range [0, {vocab})")
        need = self._budget(len(prompt), max_new)
        if need > self.engine.slot_capacity:
            telemetry.counter("serving.rejected.shape").inc()
            raise BadRequestError(
                f"prompt+max_new needs {need} positions > slot "
                f"capacity {self.engine.slot_capacity} "
                f"(pages_per_slot * page_size)")
        ms = timeout_ms if timeout_ms is not None else self.timeout_ms
        deadline = (time.perf_counter() + ms / 1e3
                    if ms is not None else None)
        rid = slo.next_request_id()
        with self._cv:
            if self._closed:
                raise ServingClosedError("server is draining/closed")
            if len(self._q) >= self.queue_depth:
                telemetry.counter("serving.rejected.queue_full").inc()
                raise QueueFullError(
                    f"queue at depth {self.queue_depth}; load shed")
            r = _Request(prompt, max_new, eos, deadline, rid)
            r.span = tracing.begin("serving.request", request_id=rid,
                                   kind="generate")
            self._q.append(r)
            self._gauge_q.set(len(self._q))
            self._cv.notify()
        return r.future

    def _budget(self, prompt_len: int, max_new: int) -> int:
        """Positions a request can ever touch — the speculative window
        may write up to ``spec_k`` past the last committed token."""
        extra = self.engine.spec_k if self.engine.spec_enabled else 0
        return prompt_len + max_new + extra

    # -- completion helpers --------------------------------------------------

    def _observe(self, r: _Request, ok: bool, error: str = "") -> None:
        now = time.perf_counter()
        entry = {
            "id": r.rid, "ok": ok, "kind": "generate",
            "latency_ms": round((now - r.t_submit) * 1e3, 3),
            "queue_ms": round(((r.t_admit or now) - r.t_submit) * 1e3, 3),
            "ts": round(time.time(), 3)}
        if r.ttft_ms is not None:
            entry["ttft_ms"] = r.ttft_ms
        if error:
            entry["error"] = error
        slo.observe_request(entry)

    def _finish_ok(self, r: _Request) -> None:
        tracing.end(r.span, tokens=len(r.generated),
                    ttft_ms=r.ttft_ms)
        self._observe(r, ok=True)
        r.future.set_result(list(r.generated))

    def _finish_error(self, r: _Request, exc: Exception) -> None:
        tracing.end(r.span, error=type(exc).__name__)
        self._observe(r, ok=False, error=type(exc).__name__)
        r.future.set_exception(exc)

    # -- the step ------------------------------------------------------------

    def step(self) -> dict:
        """One scheduler turn: expire → admit → prefill → decode →
        account.  Returns the decode extras dict it recorded."""
        with self._step_lock:
            return self._step_locked()

    def _step_locked(self) -> dict:
        eng = self.engine
        t_step = time.perf_counter()
        token = telemetry.begin_step()
        now = time.perf_counter()
        evictions = 0
        new_tokens = 0
        prefill_tokens = 0
        ttfts: List[float] = []
        completed = 0

        # 1. expire queued requests
        with self._cv:
            live = deque()
            for r in self._q:
                if r.deadline is not None and now > r.deadline:
                    telemetry.counter("serving.timeouts").inc()
                    self._finish_error(r, RequestTimeoutError(
                        "request expired in queue before admission"))
                else:
                    live.append(r)
            if len(live) != len(self._q):
                self._q = live
            self._gauge_q.set(len(self._q))

        # 1b. evict overdue active slots (frees their pages)
        for s, r in enumerate(self._slots):
            if r is None or r.deadline is None or now <= r.deadline:
                continue
            eng.release_slot(s)
            self._slots[s] = None
            evictions += 1
            telemetry.counter("decode.evictions").inc()
            telemetry.counter("serving.timeouts").inc()
            self._finish_error(r, RequestTimeoutError(
                "deadline expired mid-generation; slot evicted"))

        # 2. admit into free slots while the page budget fits
        with self._cv:
            for s in range(len(self._slots)):
                if self._slots[s] is not None or not self._q:
                    continue
                r = self._q[0]
                need = self._budget(len(r.prompt), r.max_new)
                if not eng.can_admit(need):
                    break            # head-of-line: preserve order
                self._q.popleft()
                try:
                    eng.acquire_slot(s, need)
                except OutOfPagesError:
                    self._q.appendleft(r)
                    break
                r.t_admit = now
                self._slots[s] = r
                tracing.instant("decode.admit", request_id=r.rid,
                                slot=s, prompt_tokens=len(r.prompt))
            self._gauge_q.set(len(self._q))

        # 3. chunked prefill — one chunk per prefilling slot per step
        for s, r in enumerate(self._slots):
            if r is None or r.prefilled >= len(r.prompt):
                continue
            chunk = r.prompt[r.prefilled:
                             r.prefilled + eng.prefill_chunk]
            t0 = time.perf_counter()
            nxt = eng.prefill_chunk_step(s, chunk, r.prefilled)
            tracing.record_span("decode.prefill", t0,
                                time.perf_counter(), request_id=r.rid,
                                slot=s, tokens=len(chunk))
            r.prefilled += len(chunk)
            prefill_tokens += len(chunk)
            telemetry.counter("decode.prefill_tokens").inc(len(chunk))
            if r.prefilled >= len(r.prompt):
                # final chunk: first generated token → TTFT
                r.ttft_ms = round(
                    (time.perf_counter() - r.t_submit) * 1e3, 3)
                ttfts.append(r.ttft_ms)
                r.pos_next = len(r.prompt)
                new_tokens += 1
                if self._commit(s, r, int(nxt)):
                    completed += 1

        # 4. one batched decode step over every decoding slot
        decoding = [s for s, r in enumerate(self._slots)
                    if r is not None and r.pending is not None]
        if decoding:
            n = eng.max_slots
            toks = onp.zeros((n,), onp.int32)
            pos = onp.zeros((n,), onp.int32)
            act = onp.zeros((n,), bool)
            for s in decoding:
                r = self._slots[s]
                toks[s], pos[s], act[s] = r.pending, r.pos_next, True
            if eng.spec_enabled:
                greedy, accepted = eng.spec_step(toks, pos, act)
                k = eng.spec_k
                for s in decoding:
                    r = self._slots[s]
                    take = int(accepted[s]) + 1
                    self._spec_proposed += k
                    self._spec_accepted += int(accepted[s])
                    done = False
                    for j in range(take):
                        new_tokens += 1
                        if self._commit(s, r, int(greedy[s, j])):
                            completed += 1
                            done = True
                            break
                    if not done:
                        r.pos_next += take
                telemetry.counter("decode.spec_proposed").inc(
                    k * len(decoding))
                telemetry.counter("decode.spec_accepted").inc(
                    sum(int(accepted[s]) for s in decoding))
                if self._spec_proposed:
                    telemetry.gauge("decode.spec_accept_rate").set(
                        round(self._spec_accepted
                              / self._spec_proposed, 4))
            else:
                nxt = eng.decode_step(toks, pos, act)
                for s in decoding:
                    r = self._slots[s]
                    new_tokens += 1
                    if self._commit(s, r, int(nxt[s])):
                        completed += 1
                    else:
                        r.pos_next += 1

        # 5. account
        active = self.active()
        telemetry.counter("decode.tokens").inc(new_tokens)
        telemetry.counter("decode.steps").inc()
        telemetry.gauge("decode.slots_active").set(active)
        compiles = eng.compiles - self._last_compiles
        self._last_compiles = eng.compiles
        extra = {
            "tokens": new_tokens,
            "prefill_tokens": prefill_tokens,
            "slots_active": active,
            "max_slots": eng.max_slots,
            "pages_used": eng.cache.pages_used(),
            "num_pages": eng.num_pages,
            "evictions": evictions,
            "completed": completed,
            "queue_depth": self.pending(),
            "compiles": compiles,
            "spec_proposed": self._spec_proposed,
            "spec_accepted": self._spec_accepted,
            "step_ms": round((time.perf_counter() - t_step) * 1e3, 3),
        }
        if ttfts:
            extra["ttft_ms"] = ttfts
        telemetry.end_step(token, "serving.DecodeScheduler",
                           extra={"decode": extra})
        return extra

    def _commit(self, s: int, r: _Request, tok: int) -> bool:
        """Append one emitted token; on eos/max_new finish the request,
        release its pages and free the slot.  Returns True when the
        request completed, else leaves ``tok`` as the slot's pending
        token (the caller advances ``pos_next``)."""
        r.generated.append(tok)
        if (len(r.generated) >= r.max_new
                or (r.eos is not None and tok == r.eos)):
            self.engine.release_slot(s)
            self._slots[s] = None
            self._finish_ok(r)
            return True
        r.pending = tok
        return False

    # -- background loop -----------------------------------------------------

    def _loop(self):
        idle_wait = _getenv_float("MXNET_DECODE_IDLE_WAIT_S", 0.005)
        while True:
            with self._cv:
                has_work = bool(self._q) or any(
                    r is not None for r in self._slots)
                if self._closed and not (self._drain and has_work):
                    break
                if not has_work:
                    self._cv.wait(idle_wait)
                    continue
            self.step()

    def stats(self) -> dict:
        return {
            "queue_depth": self.pending(),
            "slots_active": self.active(),
            "max_slots": self.engine.max_slots,
            "pages_used": self.engine.cache.pages_used(),
            "spec_proposed": self._spec_proposed,
            "spec_accepted": self._spec_accepted,
            "compiles": self.engine.compiles,
            "closed": self._closed,
        }
