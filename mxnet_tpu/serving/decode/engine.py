"""Decode engine: fixed-shape compiled executables over paged KV state.

Every device-side path is ONE jit-compiled executable per static
shape, compiled lazily on first use and reused forever (the
fixed-shape-executable invariant):

- ``decode_step`` — one token per active slot over the full
  ``(max_slots,)`` grid: active-slot mask, per-slot positions and page
  tables are traced int arrays, so admission/completion NEVER
  recompiles;
- ``prefill[bucket]`` — one prompt chunk for one slot, chunk length
  padded into pow2 sequence buckets (chunked prefill: long prompts
  are fed bucket-by-bucket so running decodes aren't stalled behind
  one long prompt);
- ``draft``/``verify`` — the speculative path: the draft model
  proposes ``k`` tokens per slot (its own paged KV pool, same page
  geometry, shared page tables), then the target model scores all
  ``k+1`` positions in a single dispatch and accepts the longest
  matching prefix on device (greedy speculative decode is
  token-identical to the non-speculative path by construction: every
  emitted token is the target's own argmax).

Attention inside ``decode_step``/``verify`` runs through the
``paged_attention`` kernel registrant (ops/paged_attention.py) and all
rotary embeddings through the ``rope`` registrant (ops/rope.py), so
block configs resolve through the kernel autotune cache exactly like
flash attention in training.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as onp

import jax
import jax.numpy as jnp
from jax import lax

from ... import telemetry
from ...log import get_logger
from ...ops.paged_attention import paged_attention
from ...ops.rope import rope, rope_reference
from .paged_kv import PagedKVCache

__all__ = ["DecodeModel", "DecodeEngine"]

_NEG_INF = -1e30


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, default))
    except ValueError:
        return default
    return v if v > 0 else default


def _pow2(n: int, floor: int) -> int:
    b = max(1, floor)
    while b < n:
        b *= 2
    return b


def _rms(x, g, eps=1e-6):
    xf = x.astype(jnp.float32)
    scale = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * g


class DecodeModel:
    """A small causal LM as a plain parameter pytree + pure functions.

    Deliberately framework-free (no gluon Block machinery): the decode
    executables trace straight jnp math over ``self.params``, which is
    what lets the engine AOT-compile them against fixed shapes.  The
    LM head is tied to the embedding."""

    def __init__(self, vocab_size: int, *, dim: int = 64,
                 n_heads: int = 4, n_layers: int = 2, mlp_ratio: int = 2,
                 rope_base: float = 10000.0, seed: int = 0,
                 dtype="float32"):
        if dim % n_heads:
            raise ValueError(f"dim {dim} not divisible by heads {n_heads}")
        if (dim // n_heads) % 2:
            raise ValueError("head_dim must be even for rope")
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.head_dim = dim // n_heads
        self.rope_base = float(rope_base)
        rng = onp.random.RandomState(seed)

        def mat(*shape, scale):
            return jnp.asarray(rng.randn(*shape) * scale, dtype=dtype)

        w = 1.0 / (dim ** 0.5)
        layers = []
        for _ in range(n_layers):
            layers.append({
                "ln1": jnp.ones((dim,), dtype=dtype),
                "wq": mat(dim, dim, scale=w),
                "wk": mat(dim, dim, scale=w),
                "wv": mat(dim, dim, scale=w),
                "wo": mat(dim, dim, scale=w),
                "ln2": jnp.ones((dim,), dtype=dtype),
                "w1": mat(dim, mlp_ratio * dim, scale=w),
                "w2": mat(mlp_ratio * dim, dim,
                          scale=1.0 / ((mlp_ratio * dim) ** 0.5)),
            })
        self.params: Dict[str, Any] = {
            "embed": mat(vocab_size, dim, scale=0.5),
            "layers": layers,
            "lnf": jnp.ones((dim,), dtype=dtype),
        }

    # -- dense full-recompute oracle (tests pin the paged path to it) --------

    def _ref_logits_last(self, tokens):
        """Last-position logits of a dense causal forward over the
        whole sequence — O(T^2) recompute, eager, test-only."""
        t = tokens.shape[0]
        pos = jnp.arange(t, dtype=jnp.int32)
        x = self.params["embed"][tokens]
        h_, hd = self.n_heads, self.head_dim
        scale = 1.0 / (hd ** 0.5)
        for lp in self.params["layers"]:
            h1 = _rms(x, lp["ln1"])
            q = rope_reference((h1 @ lp["wq"]).reshape(t, h_, hd), pos,
                               base=self.rope_base)
            k = rope_reference((h1 @ lp["wk"]).reshape(t, h_, hd), pos,
                               base=self.rope_base)
            v = (h1 @ lp["wv"]).reshape(t, h_, hd)
            s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                           k.astype(jnp.float32)) * scale
            qp = lax.broadcasted_iota(jnp.int32, s.shape, 1)
            kp = lax.broadcasted_iota(jnp.int32, s.shape, 2)
            s = jnp.where(qp >= kp, s, _NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("hqk,khd->qhd", p, v.astype(jnp.float32))
            x = x + o.reshape(t, self.dim).astype(x.dtype) @ lp["wo"]
            h2 = _rms(x, lp["ln2"])
            x = x + jax.nn.gelu(h2 @ lp["w1"]) @ lp["w2"]
        x = _rms(x, self.params["lnf"])
        return x[-1] @ self.params["embed"].T

    def greedy_reference(self, prompt, max_new_tokens: int,
                         eos: Optional[int] = None) -> List[int]:
        """Reference greedy generation (dense attention, full recompute
        per token).  Returns the generated tokens only."""
        toks = [int(t) for t in prompt]
        out: List[int] = []
        for _ in range(int(max_new_tokens)):
            nxt = int(jnp.argmax(self._ref_logits_last(
                jnp.asarray(toks, jnp.int32))))
            out.append(nxt)
            toks.append(nxt)
            if eos is not None and nxt == int(eos):
                break
        return out


# -- traced cores ------------------------------------------------------------

def _write_kv(pool, li, idx, k, v):
    """Scatter this step's K/V rows into layer ``li``'s page pool.
    ``idx`` carries the flat (page*page_size + offset) position per
    row, with out-of-range sentinels for masked rows (mode='drop')."""
    layers, _, num_pages, ps, h_, hd = pool.shape
    kflat = pool[li, 0].reshape(num_pages * ps, h_, hd)
    vflat = pool[li, 1].reshape(num_pages * ps, h_, hd)
    kflat = kflat.at[idx].set(k.astype(pool.dtype), mode="drop")
    vflat = vflat.at[idx].set(v.astype(pool.dtype), mode="drop")
    pool = pool.at[li, 0].set(kflat.reshape(num_pages, ps, h_, hd))
    return pool.at[li, 1].set(vflat.reshape(num_pages, ps, h_, hd))


def _decode_core(mdl: DecodeModel, params, pool, tokens, positions,
                 tables, active):
    """Consume one token per slot at ``positions`` (writing its KV),
    return (pool, argmax next token per slot)."""
    s_ = tokens.shape[0]
    h_, hd = mdl.n_heads, mdl.head_dim
    num_pages, ps = pool.shape[2], pool.shape[3]
    x = params["embed"][tokens]
    lengths = jnp.where(active, positions + 1, 0).astype(jnp.int32)
    pagerow = jnp.take_along_axis(
        tables, (positions // ps)[:, None], axis=1)[:, 0]
    flat = pagerow * ps + positions % ps
    idx = jnp.where(active, flat, num_pages * ps).astype(jnp.int32)
    for li, lp in enumerate(params["layers"]):
        h1 = _rms(x, lp["ln1"])
        q = rope((h1 @ lp["wq"]).reshape(s_, h_, hd), positions,
                 base=mdl.rope_base)
        k = rope((h1 @ lp["wk"]).reshape(s_, h_, hd), positions,
                 base=mdl.rope_base)
        v = (h1 @ lp["wv"]).reshape(s_, h_, hd)
        pool = _write_kv(pool, li, idx, k, v)
        attn = paged_attention(q, pool[li, 0], pool[li, 1], tables,
                               lengths)
        x = x + attn.reshape(s_, mdl.dim).astype(x.dtype) @ lp["wo"]
        h2 = _rms(x, lp["ln2"])
        x = x + jax.nn.gelu(h2 @ lp["w1"]) @ lp["w2"]
    x = _rms(x, params["lnf"])
    logits = x @ params["embed"].T
    return pool, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _verify_core(mdl: DecodeModel, params, pool, tokens, base_pos,
                 tables, active):
    """Target-model scoring of a ``(slots, k+1)`` speculative window in
    one dispatch: writes KV for every window position, computes greedy
    targets at each, and resolves the accepted prefix length on
    device.  Attention per window offset goes through the SAME
    paged_attention kernel as decode_step, so accepted tokens are
    bitwise those the non-speculative path would emit."""
    s_, w_ = tokens.shape
    h_, hd = mdl.n_heads, mdl.head_dim
    num_pages, ps = pool.shape[2], pool.shape[3]
    pos = base_pos[:, None] + jnp.arange(w_, dtype=jnp.int32)[None, :]
    x = params["embed"][tokens]                       # (S, W, dim)
    pagerow = jnp.take_along_axis(tables, pos // ps, axis=1)
    flat = pagerow * ps + pos % ps
    idx = jnp.where(active[:, None], flat,
                    num_pages * ps).astype(jnp.int32).reshape(s_ * w_)
    for li, lp in enumerate(params["layers"]):
        h1 = _rms(x, lp["ln1"])
        q = rope((h1 @ lp["wq"]).reshape(s_, w_, h_, hd), pos,
                 base=mdl.rope_base)
        k = rope((h1 @ lp["wk"]).reshape(s_, w_, h_, hd), pos,
                 base=mdl.rope_base)
        v = (h1 @ lp["wv"]).reshape(s_, w_, h_, hd)
        pool = _write_kv(pool, li, idx,
                         k.reshape(s_ * w_, h_, hd),
                         v.reshape(s_ * w_, h_, hd))
        cols = []
        for j in range(w_):
            lens_j = jnp.where(active, base_pos + j + 1,
                               0).astype(jnp.int32)
            cols.append(paged_attention(q[:, j], pool[li, 0],
                                        pool[li, 1], tables, lens_j))
        attn = jnp.stack(cols, axis=1)                # (S, W, H, hd)
        x = x + attn.reshape(s_, w_, mdl.dim).astype(x.dtype) @ lp["wo"]
        h2 = _rms(x, lp["ln2"])
        x = x + jax.nn.gelu(h2 @ lp["w1"]) @ lp["w2"]
    x = _rms(x, params["lnf"])
    logits = x @ params["embed"].T                    # (S, W, V)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    drafts = tokens[:, 1:]
    eq = (drafts == greedy[:, :-1]).astype(jnp.int32)
    accepted = jnp.cumprod(eq, axis=1).sum(axis=1)    # (S,)
    return pool, greedy, accepted


def _draft_core(mdl: DecodeModel, params, pool, tokens, base_pos,
                tables, active, k: int):
    """k+1 chained draft decode steps (unrolled — ``k`` is static):
    proposes k tokens and leaves the draft pool position-aligned with
    the target's write window (positions base..base+k)."""
    tok = tokens
    outs = []
    for j in range(k + 1):
        pool, tok = _decode_core(mdl, params, pool, tok, base_pos + j,
                                 tables, active)
        outs.append(tok)
    return pool, jnp.stack(outs[:k], axis=1)          # (S, k)


def _prefill_core(mdl: DecodeModel, params, pool, tokens, start,
                  chunk_len, table):
    """One prompt chunk for ONE slot: ``tokens (bucket,)`` padded,
    ``start``/``chunk_len`` traced scalars, ``table (pages_per_slot,)``
    the slot's page row.  Writes the chunk's KV and returns the greedy
    next token after the chunk's last valid position (meaningful only
    on the final chunk)."""
    b_ = tokens.shape[0]
    h_, hd = mdl.n_heads, mdl.head_dim
    num_pages, ps = pool.shape[2], pool.shape[3]
    scale = 1.0 / (hd ** 0.5)
    pos = start + jnp.arange(b_, dtype=jnp.int32)
    valid = jnp.arange(b_) < chunk_len
    total = start + chunk_len
    x = params["embed"][tokens]
    page = table[pos // ps]
    idx = jnp.where(valid, page * ps + pos % ps,
                    num_pages * ps).astype(jnp.int32)
    p_ = table.shape[0]
    for li, lp in enumerate(params["layers"]):
        h1 = _rms(x, lp["ln1"])
        q = rope((h1 @ lp["wq"]).reshape(b_, h_, hd), pos,
                 base=mdl.rope_base)
        k = rope((h1 @ lp["wk"]).reshape(b_, h_, hd), pos,
                 base=mdl.rope_base)
        v = (h1 @ lp["wv"]).reshape(b_, h_, hd)
        pool = _write_kv(pool, li, idx, k, v)
        # chunk attends its causal prefix (earlier chunks included)
        # over the slot's gathered pages — the chunk itself was just
        # written, so one mask covers intra- and cross-chunk keys
        kctx = pool[li, 0][table].reshape(p_ * ps, h_, hd)
        vctx = pool[li, 1][table].reshape(p_ * ps, h_, hd)
        s = jnp.einsum("bhd,khd->bhk", q.astype(jnp.float32),
                       kctx.astype(jnp.float32)) * scale
        kpos = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        mask = (kpos <= pos[:, None, None]) & (kpos < total)
        s = jnp.where(mask, s, _NEG_INF)
        m = s.max(axis=-1, keepdims=True)
        pr = jnp.where(mask, jnp.exp(s - m), 0.0)
        l = pr.sum(axis=-1, keepdims=True)
        l = jnp.where(l == 0.0, 1.0, l)
        attn = jnp.einsum("bhk,khd->bhd", pr / l,
                          vctx.astype(jnp.float32))
        x = x + attn.reshape(b_, mdl.dim).astype(x.dtype) @ lp["wo"]
        h2 = _rms(x, lp["ln2"])
        x = x + jax.nn.gelu(h2 @ lp["w1"]) @ lp["w2"]
    x = _rms(x, params["lnf"])
    last = lax.dynamic_index_in_dim(x, jnp.maximum(chunk_len - 1, 0),
                                    axis=0, keepdims=False)
    logits = last @ params["embed"].T
    return pool, jnp.argmax(logits).astype(jnp.int32)


# -- the engine --------------------------------------------------------------

class DecodeEngine:
    """Owns the model(s), the paged KV pools, and the compiled
    executables.  All knobs default from the environment:
    ``MXNET_DECODE_SLOTS`` / ``MXNET_DECODE_PAGES`` /
    ``MXNET_DECODE_PAGE_SIZE`` / ``MXNET_DECODE_SPEC_K``."""

    def __init__(self, model: DecodeModel, *,
                 draft_model: Optional[DecodeModel] = None,
                 spec_k: Optional[int] = None,
                 max_slots: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 page_size: Optional[int] = None,
                 pages_per_slot: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefill_floor: int = 16):
        self.model = model
        self.draft = draft_model
        self.max_slots = (int(max_slots) if max_slots is not None
                          else _env_int("MXNET_DECODE_SLOTS", 8))
        self.page_size = (int(page_size) if page_size is not None
                          else _env_int("MXNET_DECODE_PAGE_SIZE", 16))
        self.num_pages = (int(num_pages) if num_pages is not None
                          else _env_int("MXNET_DECODE_PAGES", 256))
        self.spec_k = (int(spec_k) if spec_k is not None
                       else _env_int("MXNET_DECODE_SPEC_K", 4))
        self.prefill_chunk = (int(prefill_chunk)
                              if prefill_chunk is not None
                              else _env_int("MXNET_DECODE_PREFILL_CHUNK",
                                            128))
        self.prefill_floor = min(int(prefill_floor), self.prefill_chunk)
        if draft_model is not None:
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError("draft/target vocab sizes differ")
        self.cache = PagedKVCache(
            layers=model.n_layers, num_pages=self.num_pages,
            page_size=self.page_size, heads=model.n_heads,
            head_dim=model.head_dim, max_slots=self.max_slots,
            pages_per_slot=pages_per_slot)
        self.draft_cache = None
        if draft_model is not None:
            self.draft_cache = PagedKVCache(
                layers=draft_model.n_layers, num_pages=self.num_pages,
                page_size=self.page_size, heads=draft_model.n_heads,
                head_dim=draft_model.head_dim, max_slots=self.max_slots,
                pages_per_slot=self.cache.pages_per_slot)
        self._exec: Dict[str, Any] = {}
        self.compiles = 0

    # -- properties ----------------------------------------------------------

    @property
    def spec_enabled(self) -> bool:
        return self.draft is not None and self.spec_k >= 1

    @property
    def slot_capacity(self) -> int:
        return self.cache.slot_capacity

    def prefill_bucket(self, n: int) -> int:
        return min(_pow2(n, self.prefill_floor), self.prefill_chunk)

    # -- compiled-executable plumbing ---------------------------------------

    @staticmethod
    def _model_fp(mdl):
        """Architecture fingerprint of one model for artifact keys —
        everything the traced cores bake in besides the arg shapes."""
        if mdl is None:
            return None
        return (mdl.vocab_size, mdl.dim, mdl.n_heads, mdl.n_layers,
                mdl.head_dim, mdl.rope_base)

    def _artifact_sig(self, key: str, args):
        """Content signature of one decode executable: the exec key,
        both model architectures, the engine's KV/spec geometry, and
        the exact arg pytree (structure + leaf shapes/dtypes)."""
        leaves, treedef = jax.tree_util.tree_flatten(args)
        return (key, self._model_fp(self.model), self._model_fp(self.draft),
                self.spec_k, self.max_slots, self.page_size, self.num_pages,
                str(treedef),
                tuple((tuple(jnp.shape(l)), str(jnp.result_type(l)))
                      for l in leaves))

    def _get_exec(self, key: str, fn, args):
        """Load-or-compile one executable WITHOUT running it.  Order:
        in-process memo → artifact store (deserialize; ``compiles``
        stays 0) → jit compile (ticks ``compiles``, commits back)."""
        ex = self._exec.get(key)
        if ex is not None:
            return ex
        from ... import artifacts
        asig = self._artifact_sig(key, args)
        art = artifacts.load("decode_exec", asig)
        if art is not None:
            self._exec[key] = art.compiled
            return art.compiled
        donate = ((1,) if jax.default_backend() == "tpu" else ())
        t0 = time.perf_counter()
        ex = jax.jit(fn, donate_argnums=donate).lower(*args).compile()
        telemetry.record_compile(time.perf_counter() - t0, "decode")
        self._exec[key] = ex
        self.compiles += 1
        artifacts.save("decode_exec", asig, ex, meta={"exec_key": key})
        return ex

    def _call(self, key: str, fn, args):
        return self._get_exec(key, fn, args)(*args)

    def _tables(self, cache) -> jnp.ndarray:
        return jnp.asarray(cache.tables, jnp.int32)

    def warmup(self, prefill_lengths: Sequence[int] = (1,)) -> List[str]:
        """Materialize every executable this engine will dispatch —
        decode (+ draft/verify under speculation) and one prefill per
        bucket covering ``prefill_lengths`` — WITHOUT running any of
        them.  Against a populated artifact store each one deserializes
        (``compiles`` stays 0); otherwise this pays the compiles ahead
        of traffic.  Also prefetches the kernel-autotune cache.
        Returns the exec keys materialized."""
        from ... import kernels
        n_kern = kernels.warm_cache()
        if n_kern:
            get_logger("mxnet_tpu.serving.decode").info(
                "warmup: %d tuned kernel config(s) preloaded", n_kern)
        mdl, keys = self.model, []
        s = self.max_slots
        tok = jnp.zeros((s,), jnp.int32)
        pos = jnp.zeros((s,), jnp.int32)
        act = jnp.zeros((s,), bool)
        self._get_exec(
            "decode",
            lambda p, kv, t, po, tb, a:
            _decode_core(mdl, p, kv, t, po, tb, a),
            (mdl.params, self.cache.pool, tok, pos,
             self._tables(self.cache), act))
        keys.append("decode")
        if self.spec_enabled:
            dm, k = self.draft, self.spec_k
            self._get_exec(
                "draft",
                lambda p, kv, t, po, tb, a:
                _draft_core(dm, p, kv, t, po, tb, a, k),
                (dm.params, self.draft_cache.pool, tok, pos,
                 self._tables(self.draft_cache), act))
            window = jnp.zeros((s, k + 1), jnp.int32)
            self._get_exec(
                "verify",
                lambda p, kv, t, po, tb, a:
                _verify_core(mdl, p, kv, t, po, tb, a),
                (mdl.params, self.cache.pool, window, pos,
                 self._tables(self.cache), act))
            keys += ["draft", "verify"]
        for bucket in sorted({self.prefill_bucket(int(n))
                              for n in prefill_lengths}):
            padded = jnp.zeros((bucket,), jnp.int32)
            start = jnp.asarray(0, jnp.int32)
            clen = jnp.asarray(1, jnp.int32)
            row = jnp.asarray(self.cache.tables[0], jnp.int32)
            self._get_exec(
                f"prefill_b{bucket}",
                lambda p, kv, t, st, cl, tb:
                _prefill_core(mdl, p, kv, t, st, cl, tb),
                (mdl.params, self.cache.pool, padded, start, clen, row))
            keys.append(f"prefill_b{bucket}")
            if self.draft_cache is not None:
                dm = self.draft
                drow = jnp.asarray(self.draft_cache.tables[0], jnp.int32)
                self._get_exec(
                    f"draft_prefill_b{bucket}",
                    lambda p, kv, t, st, cl, tb:
                    _prefill_core(dm, p, kv, t, st, cl, tb),
                    (dm.params, self.draft_cache.pool, padded, start,
                     clen, drow))
                keys.append(f"draft_prefill_b{bucket}")
        return keys

    # -- device steps --------------------------------------------------------

    def decode_step(self, tokens, positions, active):
        """One non-speculative engine step over the full slot grid.
        Returns the next token per slot (host numpy)."""
        mdl = self.model
        args = (mdl.params, self.cache.pool,
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                self._tables(self.cache),
                jnp.asarray(active, bool))
        pool, nxt = self._call(
            "decode",
            lambda p, kv, t, po, tb, a:
            _decode_core(mdl, p, kv, t, po, tb, a), args)
        self.cache.pool = pool
        return onp.asarray(nxt)

    def spec_step(self, tokens, base_pos, active):
        """Draft k proposals then verify in one target dispatch.
        Returns (greedy (S, k+1), accepted (S,)) host numpy."""
        mdl, dm, k = self.model, self.draft, self.spec_k
        tok = jnp.asarray(tokens, jnp.int32)
        pos = jnp.asarray(base_pos, jnp.int32)
        act = jnp.asarray(active, bool)
        dargs = (dm.params, self.draft_cache.pool, tok, pos,
                 self._tables(self.draft_cache), act)
        dpool, props = self._call(
            "draft",
            lambda p, kv, t, po, tb, a:
            _draft_core(dm, p, kv, t, po, tb, a, k), dargs)
        self.draft_cache.pool = dpool
        window = jnp.concatenate([tok[:, None], props], axis=1)
        vargs = (mdl.params, self.cache.pool, window, pos,
                 self._tables(self.cache), act)
        pool, greedy, accepted = self._call(
            "verify",
            lambda p, kv, t, po, tb, a:
            _verify_core(mdl, p, kv, t, po, tb, a), vargs)
        self.cache.pool = pool
        return onp.asarray(greedy), onp.asarray(accepted)

    def prefill_chunk_step(self, slot: int, chunk, start: int) -> int:
        """Feed one prompt chunk for ``slot`` (padded into its pow2
        bucket); returns the greedy next token after the chunk."""
        mdl = self.model
        bucket = self.prefill_bucket(len(chunk))
        padded = onp.zeros((bucket,), onp.int32)
        padded[:len(chunk)] = chunk
        args = (mdl.params, self.cache.pool, jnp.asarray(padded),
                jnp.asarray(start, jnp.int32),
                jnp.asarray(len(chunk), jnp.int32),
                jnp.asarray(self.cache.tables[slot], jnp.int32))
        pool, nxt = self._call(
            f"prefill_b{bucket}",
            lambda p, kv, t, st, cl, tb:
            _prefill_core(mdl, p, kv, t, st, cl, tb), args)
        self.cache.pool = pool
        if self.draft_cache is not None:
            dm = self.draft
            dargs = (dm.params, self.draft_cache.pool,
                     jnp.asarray(padded), jnp.asarray(start, jnp.int32),
                     jnp.asarray(len(chunk), jnp.int32),
                     jnp.asarray(self.draft_cache.tables[slot],
                                 jnp.int32))
            dpool, _ = self._call(
                f"draft_prefill_b{bucket}",
                lambda p, kv, t, st, cl, tb:
                _prefill_core(dm, p, kv, t, st, cl, tb), dargs)
            self.draft_cache.pool = dpool
        return int(nxt)

    # -- slot page lifecycle -------------------------------------------------

    def acquire_slot(self, slot: int, tokens: int) -> None:
        self.cache.acquire(slot, tokens)
        if self.draft_cache is not None:
            try:
                self.draft_cache.acquire(slot, tokens)
            except Exception:
                self.cache.release(slot)
                raise

    def release_slot(self, slot: int) -> int:
        n = self.cache.release(slot)
        if self.draft_cache is not None:
            self.draft_cache.release(slot)
        return n

    def can_admit(self, tokens: int) -> bool:
        need = self.cache.pages_for(tokens)
        ok = self.cache.allocator.available >= need
        if self.draft_cache is not None:
            ok = ok and self.draft_cache.allocator.available >= need
        return ok

    def stats(self) -> dict:
        return {"compiles": self.compiles,
                "executables": sorted(self._exec),
                "max_slots": self.max_slots,
                "page_size": self.page_size,
                "num_pages": self.num_pages,
                "pages_used": self.cache.pages_used(),
                "slot_capacity": self.slot_capacity,
                "spec_k": self.spec_k if self.spec_enabled else 0}
