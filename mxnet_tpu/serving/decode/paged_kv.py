"""Paged KV cache: pre-allocated device pool + host page allocator.

The device side is ONE array per engine, ``(layers, 2, num_pages,
page_size, heads, head_dim)`` (k and v stacked on axis 1), allocated
once at construction and threaded through every compiled decode/
prefill executable — sequence state never changes a shape.  The host
side is a free-list page allocator with per-slot page tables: slots
acquire pages at admission, the tables are passed to the executables
as traced ``(max_slots, pages_per_slot)`` int32 arrays, and eviction
returns pages to the free list for the next request (recycling — no
device traffic on either path).

Row ``num_pages`` — one past the pool — is the scatter sentinel: KV
writes for inactive slots / padded prefill rows are directed there and
dropped by XLA (``mode="drop"``), so masking never needs a branch.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as onp

from ... import telemetry
from ...base import MXNetError

__all__ = ["PageAllocator", "PagedKVCache", "OutOfPagesError"]


class OutOfPagesError(MXNetError):
    """The pool has no free pages for the attempted allocation."""


class PageAllocator:
    """Free-list page allocator (host-side, O(1) alloc/free)."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self._lock = threading.Lock()

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def used(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self, n: int) -> List[int]:
        with self._lock:
            if n > len(self._free):
                raise OutOfPagesError(
                    f"requested {n} pages, {len(self._free)} free "
                    f"of {self.num_pages}")
            pages = [self._free.pop() for _ in range(n)]
        return pages

    def free(self, pages: List[int]) -> None:
        with self._lock:
            self._free.extend(pages)


class PagedKVCache:
    """One engine's KV state: device pool + slot page tables.

    ``pages_per_slot`` bounds a single slot's table width (the traced
    table shape); a slot's token capacity is
    ``pages_per_slot * page_size``."""

    def __init__(self, *, layers: int, num_pages: int, page_size: int,
                 heads: int, head_dim: int, max_slots: int,
                 pages_per_slot: Optional[int] = None,
                 dtype="float32"):
        import jax.numpy as jnp
        self.layers = int(layers)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.max_slots = int(max_slots)
        self.pages_per_slot = int(
            pages_per_slot if pages_per_slot is not None
            else max(1, num_pages // max(1, max_slots)))
        self.pool = jnp.zeros(
            (self.layers, 2, self.num_pages, self.page_size,
             self.heads, self.head_dim), dtype=dtype)
        self.allocator = PageAllocator(self.num_pages)
        # traced inputs: page-table rows + a scratch row of zeros for
        # freed slots (page 0 ids are fine — masked by length 0)
        self.tables = onp.zeros((self.max_slots, self.pages_per_slot),
                                onp.int32)
        self._slot_pages: Dict[int, List[int]] = {}

    @property
    def slot_capacity(self) -> int:
        """Max tokens (prompt + generated) one slot can hold."""
        return self.pages_per_slot * self.page_size

    def pages_used(self) -> int:
        return self.allocator.used

    def pages_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)

    def acquire(self, slot: int, tokens: int) -> None:
        """Allocate pages covering ``tokens`` positions for ``slot``
        and write its table row.  Raises :class:`OutOfPagesError`
        (leaving the slot untouched) when the free list is short."""
        if slot in self._slot_pages:
            raise MXNetError(f"slot {slot} already holds pages")
        need = self.pages_for(tokens)
        if need > self.pages_per_slot:
            raise MXNetError(
                f"{tokens} tokens need {need} pages > pages_per_slot "
                f"{self.pages_per_slot}")
        pages = self.allocator.alloc(need)
        self._slot_pages[slot] = pages
        row = onp.zeros((self.pages_per_slot,), onp.int32)
        row[:need] = pages
        self.tables[slot] = row
        telemetry.gauge("decode.pages_used").set(self.pages_used())

    def release(self, slot: int) -> int:
        """Return ``slot``'s pages to the free list; returns the count
        recycled (0 when the slot held none)."""
        pages = self._slot_pages.pop(slot, None)
        if not pages:
            return 0
        self.allocator.free(pages)
        self.tables[slot] = 0
        telemetry.gauge("decode.pages_used").set(self.pages_used())
        return len(pages)

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages.get(slot, ()))
