"""Serving SLO plane: request identity, burn-rate alerting, saturation
attribution.

The training side joins its two observability planes (per-step
telemetry and clustermon incidents) at the aggregator; this module is
the same join at the SERVING boundary, built from four pieces:

- **Request identity**: :func:`next_request_id` mints the monotonic id
  the batcher stamps into every ``serving.enqueue`` /
  ``serving.request`` span (and the ``serving.dispatch`` span's
  ``request_ids`` list), so one request can be followed through
  admission → coalescing → dispatch.  :func:`observe_request` receives
  each request's latency decomposition (validate / queue wait / hold
  window / dispatch / pad-waste share / cold-compile share) and keeps a
  bounded ring of the N slowest requests (``MXNET_SERVING_SLOW_RING``)
  served at ``GET /requestz``.
- **SLO engine**: :class:`ServingSLO` evaluates declared objectives —
  a pXX latency target and an availability (error-rate) budget — over
  sliding windows with the multi-window multi-burn-rate rule: alert
  when BOTH the long window (``MXNET_SLO_WINDOW_S``) and the short
  window (long/12) burn error budget faster than ``burn_threshold``
  (14.4 ≈ "2% of a 30-day budget in an hour"), clear when the long
  window drops back under it.  Burn = breach-fraction / budget-fraction
  (a p95 target budgets 5% of requests; all-breach burns at 20×).
  Results land in ``serving_slo.*`` registry metrics (→
  ``mxnet_serving_slo_*`` Prometheus series), the per-step record's
  ``serving_slo`` section, and ``GET /slo`` on both scrape surfaces.
  A ``serving.weights_age_s`` staleness gauge
  (:func:`note_weights_published`) is wired for the future
  parameter-streaming path.
- **Incident integration**: a burning objective drives a
  :class:`clustermon.IncidentStore` — the same open / escalate / close
  state machine, ``incidents.jsonl`` persistence and
  ``cluster.incidents_total{cause=...}`` counter family the straggler
  detector uses — with serving causes ``latency_slo`` /
  ``error_budget`` / ``queue_saturation``.  Saturation attribution
  picks the cause the way the straggler rule does: the dominant
  per-request signal (queue wait vs compute vs padding waste vs cold
  compile) wins, and a dominant queue-wait names ``queue_saturation``.
  An escalated ``queue_saturation`` incident publishes batcher-tuning
  advice (raise ``max_batch``, shrink ``max_delay_ms``) through the
  advice plane, applied to live batchers under ``MXNET_REMEDIATE=1``.
- **Zero threads**: evaluation runs INLINE on the dispatch path,
  rate-limited to ~short-window/4; ``GET /slo`` forces a fresh
  evaluation so a stopped-traffic burn still clears.  With no
  objectives declared (``MXNET_SLO_LATENCY_MS`` unset, no
  :func:`declare`) and ``MXNET_TRACE=0``, nothing here runs on the
  serving path beyond the id increment — results are bitwise unchanged
  and no thread is created in any mode.

``tools/slo_report.py`` replays the same burn math over JSONL spools
offline for post-mortems.
"""
from __future__ import annotations

import heapq
import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from .. import telemetry
from .. import tracing

__all__ = ["ServingSLO", "declare", "undeclare", "declared", "get",
           "active", "next_request_id", "request_count",
           "observe_request", "slo_view", "requestz", "burning_cause",
           "note_weights_published", "weights_age_s", "note_batcher",
           "SAT_SIGNALS"]

_LOCK = threading.RLock()


def _logger():
    from ..log import get_logger
    return get_logger("mxnet_tpu.serving.slo")


def _getenv_float(name: str, default: Optional[float] = None
                  ) -> Optional[float]:
    v = os.environ.get(name)
    if not v:
        return default
    try:
        return float(v)
    except ValueError:
        return default


# -- request identity --------------------------------------------------------

_RID_LOCK = threading.Lock()
_rid = 0


def next_request_id() -> int:
    """Monotonic per-process request id — stamped by the batcher into
    every admitted request's spans so admission, coalescing and
    dispatch stay joinable."""
    global _rid
    with _RID_LOCK:
        _rid += 1
        return _rid


def request_count() -> int:
    """Ids minted so far (== requests admitted to the queue)."""
    return _rid


# -- slowest-request ring ----------------------------------------------------

def _ring_capacity() -> int:
    v = os.environ.get("MXNET_SERVING_SLOW_RING")
    try:
        return max(1, int(v)) if v else 16
    except ValueError:
        return 16


_RING_LOCK = threading.Lock()
_ring: List[tuple] = []       # min-heap of (latency_ms, seq, entry)
_ring_seq = 0


def _ring_add(entry: dict) -> None:
    global _ring_seq
    cap = _ring_capacity()
    with _RING_LOCK:
        _ring_seq += 1
        item = (float(entry.get("latency_ms") or 0.0), _ring_seq, entry)
        if len(_ring) < cap:
            heapq.heappush(_ring, item)
        elif item[0] > _ring[0][0]:
            heapq.heapreplace(_ring, item)
        while len(_ring) > cap:     # capacity shrank mid-run
            heapq.heappop(_ring)


def clear_ring() -> None:
    with _RING_LOCK:
        _ring.clear()


def requestz(limit: Optional[int] = None) -> dict:
    """The ``GET /requestz`` body: the N slowest requests served (their
    full latency decomposition), slowest first."""
    with _RING_LOCK:
        tracked = len(_ring)
        items = sorted(_ring, key=lambda it: (-it[0], it[1]))
    entries = [dict(it[2]) for it in items]
    if limit is not None:
        entries = entries[:max(0, int(limit))]
    return {"ring_capacity": _ring_capacity(), "tracked": tracked,
            "requests_seen": _rid, "slowest": entries}


# -- weights staleness (future parameter-streaming path) ---------------------

_weights_ts: Optional[float] = None


def note_weights_published(ts: Optional[float] = None) -> None:
    """Stamp a parameter-set publication.  The online-learning path
    will call this on every weight swap; until then the gauge simply
    reads 'age of the weights this server booted with' once someone
    stamps it."""
    global _weights_ts
    _weights_ts = time.time() if ts is None else float(ts)
    telemetry.gauge("serving.weights_age_s").set(0.0)


def weights_age_s() -> Optional[float]:
    """Seconds since the last published weight set (None when never
    stamped — the gauge stays unset and off /metrics)."""
    if _weights_ts is None:
        return None
    age = round(max(0.0, time.time() - _weights_ts), 3)
    telemetry.gauge("serving.weights_age_s").set(age)
    return age


# -- live-batcher registry (queue_saturation remediation target) -------------

_batchers: "weakref.WeakSet" = weakref.WeakSet()


def note_batcher(batcher) -> None:
    """Batchers self-register at construction so an escalated
    ``queue_saturation`` incident can tune the live instance under
    ``MXNET_REMEDIATE=1`` (weak refs: a drained batcher just ages
    out)."""
    _batchers.add(batcher)


# -- the SLO engine ----------------------------------------------------------

SAT_SIGNALS = ("queue_wait", "compute", "padding", "compile")


class ServingSLO:
    """Declared serving objectives evaluated over sliding windows.

    Not a thread: :meth:`observe` (the batcher's per-request feed)
    triggers a rate-limited inline evaluation; :meth:`evaluate` (the
    ``/slo`` endpoints) forces one.  Owns its own
    :class:`clustermon.IncidentStore` (persisted next to the cluster
    spools when ``MXNET_CLUSTER_DIR`` is set) and registers it with
    :func:`clustermon.incident_view` so ``GET /incidents`` shows
    serving incidents beside straggler incidents."""

    def __init__(self, latency_ms: float, percentile: float = 95.0,
                 availability: Optional[float] = None,
                 window_s: Optional[float] = None,
                 burn_threshold: Optional[float] = None,
                 min_samples: Optional[int] = None,
                 directory: Optional[str] = None,
                 ttft_ms: Optional[float] = None,
                 from_env: bool = False):
        from .. import clustermon
        self.latency_ms = float(latency_ms)
        # decode-plane time-to-first-token objective: only requests
        # that report a ttft_ms (generation requests) feed it; same
        # percentile budget as end-to-end latency
        self.ttft_ms = (float(ttft_ms)
                        if ttft_ms is not None and float(ttft_ms) > 0
                        else None)
        self.percentile = float(percentile) if percentile else 95.0
        self.availability = (float(availability)
                             if availability is not None else 0.999)
        self.window_s = float(window_s) if window_s else 60.0
        self.short_s = max(0.05, self.window_s / 12.0)
        self.burn_threshold = (float(burn_threshold)
                               if burn_threshold else 14.4)
        self.min_samples = (int(min_samples)
                            if min_samples is not None else 10)
        self.from_env = from_env
        self.directory = (directory if directory is not None
                          else (os.environ.get("MXNET_CLUSTER_DIR")
                                or None))
        # budget fractions: the share of requests ALLOWED to miss
        self._lat_budget = max(1e-6, 1.0 - self.percentile / 100.0)
        self._avail_budget = max(1e-6, 1.0 - self.availability)
        self._store = clustermon.IncidentStore(self.directory)
        self._lock = threading.RLock()
        self._samples: deque = deque()      # (t_mono, latency_ms, ok)
        self._signals: deque = deque()      # (t_mono, {signal: ms})
        self._ttft: deque = deque()         # (t_mono, ttft_ms)
        self._burning: Optional[dict] = None
        self._view: dict = {}
        self._last_eval = 0.0
        self._eval_interval = min(0.25, self.short_s / 4.0)
        self._c_req = telemetry.counter("serving_slo.requests")
        self._c_breach = telemetry.counter("serving_slo.breaches")
        self._c_err = telemetry.counter("serving_slo.errors")
        self._c_eval = telemetry.counter("serving_slo.evals")
        self._c_inc = telemetry.counter("serving_slo.incidents")
        telemetry.gauge("serving_slo.latency_target_ms").set(
            self.latency_ms)
        telemetry.gauge("serving_slo.burning").set(0)

    # -- sampling -----------------------------------------------------------

    def observe(self, entry: dict) -> None:
        """Feed one finished (or failed/expired) request.  ``entry``
        carries the batcher's latency decomposition: ``latency_ms``,
        ``ok``, and optional ``validate_ms`` / ``queue_ms`` /
        ``hold_ms`` / ``dispatch_ms`` / ``pad_share`` /
        ``compile_ms``."""
        now = time.monotonic()
        lat = float(entry.get("latency_ms") or 0.0)
        ok = bool(entry.get("ok", True))
        disp = float(entry.get("dispatch_ms") or 0.0)
        pad = float(entry.get("pad_share") or 0.0) * disp
        comp = float(entry.get("compile_ms") or 0.0)
        sig = {
            "queue_wait": (float(entry.get("queue_ms") or 0.0)
                           + float(entry.get("hold_ms") or 0.0)),
            "compute": max(0.0, disp - pad - comp),
            "padding": pad,
            "compile": comp,
        }
        ttft = entry.get("ttft_ms")
        with self._lock:
            self._samples.append((now, lat, ok))
            self._signals.append((now, sig))
            if ttft is not None and self.ttft_ms is not None:
                self._ttft.append((now, float(ttft)))
                if float(ttft) > self.ttft_ms:
                    telemetry.counter("serving_slo.ttft_breaches").inc()
            self._c_req.inc()
            if lat > self.latency_ms:
                self._c_breach.inc()
            if not ok:
                self._c_err.inc()
            if now - self._last_eval >= self._eval_interval:
                self._evaluate_locked(now)

    # -- evaluation ---------------------------------------------------------

    def evaluate(self) -> dict:
        """Force one evaluation pass (the ``/slo`` endpoints call this
        so a burn clears even after traffic stops)."""
        with self._lock:
            return self._evaluate_locked(time.monotonic())

    def view(self) -> dict:
        """The last evaluation's view (evaluating once if none ran
        yet)."""
        with self._lock:
            if not self._view:
                return self._evaluate_locked(time.monotonic())
            return dict(self._view)

    def snapshot(self, limit: int = 50) -> dict:
        """Incident-store snapshot — the clustermon extra-store
        protocol ``incident_view`` merges."""
        with self._lock:
            return self._store.snapshot(limit)

    def step_section(self) -> Optional[dict]:
        """The compact per-step-record ``serving_slo`` section
        (telemetry's provider hook)."""
        with self._lock:
            v = self._view
            if not v:
                return {"declared": True}
            lat = v.get("latency") or {}
            b = v.get("burning")
            return {"p95_ms": lat.get("p95_ms"),
                    "p99_ms": lat.get("p99_ms"),
                    "burn_long": lat.get("burn_long"),
                    "burn_short": lat.get("burn_short"),
                    "budget_remaining": lat.get("budget_remaining"),
                    "burning": b["cause"] if b else None}

    @staticmethod
    def _pct(sorted_vals: List[float], p: float) -> float:
        if not sorted_vals:
            return 0.0
        k = max(0, min(len(sorted_vals) - 1,
                       round(p / 100.0 * (len(sorted_vals) - 1))))
        return sorted_vals[k]

    def _prune(self, now: float) -> None:
        cut = now - self.window_s
        while self._samples and self._samples[0][0] < cut:
            self._samples.popleft()
        while self._signals and self._signals[0][0] < cut:
            self._signals.popleft()
        while self._ttft and self._ttft[0][0] < cut:
            self._ttft.popleft()

    def _saturation(self) -> Dict[str, float]:
        n = len(self._signals)
        out = {k: 0.0 for k in SAT_SIGNALS}
        if not n:
            return out
        for _, sig in self._signals:
            for k in SAT_SIGNALS:
                out[k] += sig.get(k, 0.0)
        return {k: round(v / n, 3) for k, v in out.items()}

    def _attribute(self, sat: Dict[str, float]) -> str:
        """Cause attribution, the straggler decision rule transplanted:
        the dominant per-request signal names the cause; a dominant
        queue-wait is ``queue_saturation``, anything else (compute /
        padding / cold compile / unattributed) burns as
        ``latency_slo``."""
        total = sum(sat.values())
        if total <= 0.0:
            return "latency_slo"
        top = max(sat, key=lambda k: sat[k])
        if sat[top] <= 0.0 or sat[top] < 0.1 * total:
            return "latency_slo"    # nothing explains the latency
        return "queue_saturation" if top == "queue_wait" \
            else "latency_slo"

    def _evaluate_locked(self, now: float) -> dict:
        from .. import clustermon
        self._last_eval = now
        self._c_eval.inc()
        self._prune(now)
        long_w = list(self._samples)
        cut_short = now - self.short_s
        short_w = [s for s in long_w if s[0] >= cut_short]
        n_long, n_short = len(long_w), len(short_w)
        lats = sorted(l for (_t, l, _ok) in long_w)
        p50 = round(self._pct(lats, 50), 3)
        p95 = round(self._pct(lats, 95), 3)
        p99 = round(self._pct(lats, 99), 3)

        def _frac(win, pred):
            return (sum(1 for s in win if pred(s)) / len(win)) \
                if win else 0.0

        lat_frac_long = _frac(long_w, lambda s: s[1] > self.latency_ms)
        lat_frac_short = _frac(short_w, lambda s: s[1] > self.latency_ms)
        err_frac_long = _frac(long_w, lambda s: not s[2])
        err_frac_short = _frac(short_w, lambda s: not s[2])
        lat_burn_long = lat_frac_long / self._lat_budget
        lat_burn_short = lat_frac_short / self._lat_budget
        av_burn_long = err_frac_long / self._avail_budget
        av_burn_short = err_frac_short / self._avail_budget
        # ttft objective (decode plane): its own sample stream — only
        # generation requests report a first-token time
        ttft_long = list(self._ttft)
        ttft_short = [s for s in ttft_long if s[0] >= cut_short]
        ttfts = sorted(v for (_t, v) in ttft_long)
        ttft_p50 = round(self._pct(ttfts, 50), 3)
        ttft_p95 = round(self._pct(ttfts, 95), 3)
        ttft_burn_long = ttft_burn_short = 0.0
        if self.ttft_ms is not None:
            ttft_burn_long = _frac(
                ttft_long,
                lambda s: s[1] > self.ttft_ms) / self._lat_budget
            ttft_burn_short = _frac(
                ttft_short,
                lambda s: s[1] > self.ttft_ms) / self._lat_budget
        sat = self._saturation()
        # multi-window multi-burn-rate rule with hysteresis: open when
        # BOTH windows exceed the threshold, close when the long window
        # drops under it (the cause is latched while burning so the
        # incident store never flaps close/open on a signal wobble)
        thr = self.burn_threshold
        enough = n_long >= self.min_samples and n_short >= 1
        enough_ttft = (self.ttft_ms is not None
                       and len(ttft_long) >= self.min_samples
                       and len(ttft_short) >= 1)
        if self._burning is None and (enough or enough_ttft):
            if enough and av_burn_long >= thr and av_burn_short >= thr:
                self._burning = {"objective": "availability",
                                 "cause": "error_budget",
                                 "since_ts": round(time.time(), 3)}
            elif enough and lat_burn_long >= thr \
                    and lat_burn_short >= thr:
                self._burning = {"objective": "latency",
                                 "cause": self._attribute(sat),
                                 "since_ts": round(time.time(), 3)}
            elif enough_ttft and ttft_burn_long >= thr \
                    and ttft_burn_short >= thr:
                self._burning = {"objective": "ttft",
                                 "cause": "ttft_slo",
                                 "since_ts": round(time.time(), 3)}
        elif self._burning is not None:
            long_burn = {"availability": av_burn_long,
                         "ttft": ttft_burn_long}.get(
                             self._burning["objective"], lat_burn_long)
            if long_burn < thr:
                self._burning = None
        if self._burning is None:
            verdict = None
            burn_rep = round(max(lat_burn_long, av_burn_long,
                                 ttft_burn_long), 3)
        else:
            burn_rep = round(
                {"availability": av_burn_long,
                 "ttft": ttft_burn_long}.get(
                     self._burning["objective"], lat_burn_long), 3)
            verdict = {"rank": clustermon.rank_world()[0],
                       "cause": self._burning["cause"],
                       "ratio": burn_rep, "step_ms": p95}
        events = self._store.observe(verdict, step=_rid,
                                     now=time.time())
        if events:
            self._handle_events(events)
        view = {
            "declared": True,
            "objectives": {
                "latency": {"target_ms": self.latency_ms,
                            "percentile": self.percentile,
                            "budget": round(self._lat_budget, 6)},
                "availability": {"target": self.availability,
                                 "budget": round(self._avail_budget,
                                                 6)},
            },
            "window": {"long_s": self.window_s,
                       "short_s": round(self.short_s, 3),
                       "burn_threshold": thr,
                       "min_samples": self.min_samples},
            "samples": {"long": n_long, "short": n_short},
            "latency": {
                "p50_ms": p50, "p95_ms": p95, "p99_ms": p99,
                "target_ms": self.latency_ms,
                "breach_fraction_long": round(lat_frac_long, 4),
                "breach_fraction_short": round(lat_frac_short, 4),
                "burn_long": round(lat_burn_long, 3),
                "burn_short": round(lat_burn_short, 3),
                "budget_remaining": round(
                    max(0.0, 1.0 - lat_burn_long), 3),
            },
            "availability": {
                "target": self.availability,
                "observed": round(1.0 - err_frac_long, 6),
                "errors": sum(1 for s in long_w if not s[2]),
                "requests": n_long,
                "burn_long": round(av_burn_long, 3),
                "burn_short": round(av_burn_short, 3),
                "budget_remaining": round(
                    max(0.0, 1.0 - av_burn_long), 3),
            },
            "saturation": sat,
            "ttft": ({
                "target_ms": self.ttft_ms,
                "p50_ms": ttft_p50, "p95_ms": ttft_p95,
                "samples": len(ttft_long),
                "burn_long": round(ttft_burn_long, 3),
                "burn_short": round(ttft_burn_short, 3),
                "budget_remaining": round(
                    max(0.0, 1.0 - ttft_burn_long), 3),
            } if self.ttft_ms is not None else None),
            "weights_age_s": weights_age_s(),
            "burning": (dict(self._burning, saturation=sat,
                             burn=burn_rep)
                        if self._burning else None),
            "incidents": {
                "open": self._store.snapshot(1)["open"],
                "counts": self._store.snapshot(1)["counts"],
            },
        }
        self._view = view
        g = telemetry.gauge
        g("serving_slo.latency_p50_ms").set(p50)
        g("serving_slo.latency_p95_ms").set(p95)
        g("serving_slo.latency_p99_ms").set(p99)
        g("serving_slo.latency_burn_long").set(round(lat_burn_long, 3))
        g("serving_slo.latency_burn_short").set(round(lat_burn_short,
                                                      3))
        g("serving_slo.latency_budget_remaining").set(
            round(max(0.0, 1.0 - lat_burn_long), 3))
        g("serving_slo.availability").set(round(1.0 - err_frac_long, 6))
        g("serving_slo.availability_burn_long").set(round(av_burn_long,
                                                          3))
        g("serving_slo.error_budget_remaining").set(
            round(max(0.0, 1.0 - av_burn_long), 3))
        if self.ttft_ms is not None:
            g("serving_slo.ttft_p50_ms").set(ttft_p50)
            g("serving_slo.ttft_p95_ms").set(ttft_p95)
            g("serving_slo.ttft_burn_long").set(
                round(ttft_burn_long, 3))
        g("serving_slo.burning").set(1 if self._burning else 0)
        g("serving_slo.burning_cause").set(
            self._burning["cause"] if self._burning else "none")
        return dict(view)

    # -- incident lifecycle -------------------------------------------------

    def _handle_events(self, events: List[dict]) -> None:
        """Bump the counter family, log, mark the trace timeline, fire
        the shared on_incident hooks, and publish queue-saturation
        advice — all inline (no aggregator thread on the serving
        side)."""
        from .. import clustermon
        for ev in events:
            inc = ev["incident"]
            if ev["event"] == "open":
                self._c_inc.inc()
                clustermon._C_INCIDENTS.inc()
                clustermon._C_INCIDENT_CAUSE.get(
                    inc["cause"],
                    clustermon._C_INCIDENT_CAUSE["unknown"]).inc()
                _logger().warning(
                    "serving SLO incident %d opened: %s burning at "
                    "%.1fx budget (p95 %.2f ms over the %gs window)",
                    inc["id"], inc["cause"], inc["peak_ratio"],
                    inc["peak_step_ms"], self.window_s)
            elif ev["event"] == "close":
                _logger().info(
                    "serving SLO incident %d closed: %s after %.1fs, "
                    "peak burn %.1fx",
                    inc["id"], inc["cause"], inc["duration_s"],
                    inc["peak_ratio"])
            if ev["event"] == "escalate" \
                    and inc["cause"] == "queue_saturation":
                self._publish_batcher_advice(inc)
            tracing.instant(f"cluster.incident.{ev['event']}",
                            incident=inc["id"], rank=inc["rank"],
                            cause=inc["cause"])
            for fn in clustermon.incident_hooks():
                try:
                    fn(ev["event"], dict(inc))
                except Exception:
                    _logger().exception("on_incident hook %r failed",
                                        fn)

    def _publish_batcher_advice(self, inc: dict) -> None:
        """Escalated queue saturation → batcher tuning through the
        advice plane: coalesce harder (double ``max_batch``) and stop
        holding for stragglers a saturated queue already provides
        (halve ``max_delay_ms``).  Published to ``advice.jsonl`` when a
        cluster dir exists; applied to live batchers only under
        ``MXNET_REMEDIATE=1`` (counted either way)."""
        from .. import clustermon
        live = [b for b in list(_batchers) if not b.closed]
        cur_mb = max([b.max_batch_size for b in live], default=32)
        cur_delay = max([b.max_delay_ms for b in live], default=2.0)
        rec = {"action": "batcher_tuning", "rank": inc["rank"],
               "max_batch": int(max(1, 2 * cur_mb)),
               "max_delay_ms": round(cur_delay / 2.0, 3),
               "incident_id": inc["id"], "cause": inc["cause"],
               "ts": round(time.time(), 3)}
        if self.directory:
            try:
                with open(os.path.join(self.directory,
                                       clustermon.ADVICE_FILE),
                          "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass
        telemetry.counter("cluster.advice_published").inc()
        if clustermon._remediate_enabled() and live:
            for b in live:
                b.max_batch_size = rec["max_batch"]
                b.max_delay_ms = max(0.0, rec["max_delay_ms"])
            telemetry.counter("cluster.advice_applied").inc()
            _logger().warning(
                "remediation applied (incident %d): batcher max_batch "
                "-> %d, max_delay_ms -> %.2f", inc["id"],
                rec["max_batch"], rec["max_delay_ms"])
        else:
            telemetry.counter("cluster.advice_ignored").inc()
            _logger().warning(
                "remediation advice published (incident %d): "
                "queue_saturation -> max_batch %d, max_delay_ms %.2f "
                "(advisory; MXNET_REMEDIATE unset)",
                inc["id"], rec["max_batch"], rec["max_delay_ms"])


# -- declaration plumbing ----------------------------------------------------
# Objectives come from either an explicit declare() call or the env
# knobs (MXNET_SLO_LATENCY_MS declares the plane; MXNET_SLO_WINDOW_S /
# MXNET_SLO_AVAILABILITY / MXNET_SLO_BURN_THRESHOLD shape it), re-read
# on every declared() check the way telemetry re-reads its sink env —
# a long-lived process can flip them without re-importing.  An explicit
# declare() owns the plane; env changes don't clobber it.

_slo: Optional[ServingSLO] = None
_env_cache: Dict[str, Any] = {"key": None}


def _declare_locked(**kw) -> ServingSLO:
    global _slo
    from .. import clustermon
    _slo = ServingSLO(**kw)
    clustermon.register_incident_store(_slo)
    telemetry.set_slo_provider(_slo.step_section)
    return _slo


def _undeclare_locked() -> None:
    global _slo
    if _slo is None:
        return
    from .. import clustermon
    clustermon.unregister_incident_store(_slo)
    telemetry.set_slo_provider(None)
    _slo = None


def _refresh_env() -> None:
    global _slo
    key = (os.environ.get("MXNET_SLO_LATENCY_MS") or None,
           os.environ.get("MXNET_SLO_WINDOW_S") or None,
           os.environ.get("MXNET_SLO_AVAILABILITY") or None,
           os.environ.get("MXNET_SLO_BURN_THRESHOLD") or None,
           os.environ.get("MXNET_SLO_TTFT_MS") or None)
    if key == _env_cache["key"]:
        return
    with _LOCK:
        if key == _env_cache["key"]:
            return
        _env_cache["key"] = key
        if _slo is not None and not _slo.from_env:
            return
        if _slo is not None:
            _undeclare_locked()
        lat = _getenv_float("MXNET_SLO_LATENCY_MS")
        if lat is not None and lat > 0:
            _declare_locked(
                latency_ms=lat,
                window_s=_getenv_float("MXNET_SLO_WINDOW_S"),
                availability=_getenv_float("MXNET_SLO_AVAILABILITY"),
                burn_threshold=_getenv_float(
                    "MXNET_SLO_BURN_THRESHOLD"),
                ttft_ms=_getenv_float("MXNET_SLO_TTFT_MS"),
                from_env=True)


def declare(latency_ms: float, percentile: float = 95.0,
            availability: Optional[float] = None,
            window_s: Optional[float] = None,
            burn_threshold: Optional[float] = None,
            min_samples: Optional[int] = None,
            directory: Optional[str] = None,
            ttft_ms: Optional[float] = None) -> ServingSLO:
    """Declare (or re-declare) the serving objectives explicitly.
    Replaces any live SLO engine, env-declared or not.  ``ttft_ms``
    adds the decode-plane time-to-first-token objective (also
    declarable via ``MXNET_SLO_TTFT_MS`` alongside
    ``MXNET_SLO_LATENCY_MS``)."""
    with _LOCK:
        _undeclare_locked()
        return _declare_locked(
            latency_ms=latency_ms, percentile=percentile,
            availability=availability, window_s=window_s,
            burn_threshold=burn_threshold, min_samples=min_samples,
            directory=directory, ttft_ms=ttft_ms, from_env=False)


def undeclare() -> None:
    """Drop the live SLO engine (tests / shutdown).  While the env
    knobs stay set, the next declared() check re-declares from them."""
    with _LOCK:
        _undeclare_locked()
        _env_cache["key"] = None


def declared() -> bool:
    _refresh_env()
    return _slo is not None


def get() -> Optional[ServingSLO]:
    _refresh_env()
    return _slo


def active() -> bool:
    """True when per-request accounting should run at all: objectives
    declared (SLO sampling) or tracing live (slow-request ring).  The
    batcher's disabled-path guard."""
    return declared() or tracing.enabled()


def observe_request(entry: dict) -> None:
    """Per-request feed from the batcher: slow-ring admission plus SLO
    sampling (each gated on its own switch)."""
    s = _slo
    if s is not None or tracing.enabled():
        _ring_add(entry)
    if s is not None:
        s.observe(entry)


def burning_cause() -> Optional[str]:
    """The currently-burning cause (None when healthy or
    undeclared)."""
    s = get()
    if s is None:
        return None
    b = s.view().get("burning")
    return b["cause"] if b else None


def slo_view() -> dict:
    """The ``GET /slo`` body (both ServingServer and the standalone
    exporter serve it).  Forces a fresh evaluation so a burn clears —
    and its incident closes — even when traffic has stopped."""
    _refresh_env()
    s = _slo
    ring = {"capacity": _ring_capacity(), "tracked": len(_ring)}
    if s is None:
        return {"declared": False, "objectives": None,
                "requests_seen": _rid, "weights_age_s": weights_age_s(),
                "ring": ring}
    view = s.evaluate()
    view["requests_seen"] = _rid
    view["ring"] = ring
    return view
