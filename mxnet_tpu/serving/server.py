"""ServingServer: in-process serving API + stdlib HTTP JSON endpoint.

The in-process surface is primary — ``predict()`` submits to the
batcher and blocks on the future, so tier-1 tests (and co-located
Python callers) exercise the full queue → batcher → bucketed-engine
path with no sockets.  The HTTP endpoint is a thin stdlib
``http.server`` shim over the same calls:

- ``POST /predict``  body ``{"data": <nested list>, "dtype"?: str,
  "timeout_ms"?: number}`` → ``{"output": <nested list>}`` (or
  ``{"outputs": [...]}`` for multi-output blocks).
- ``POST /generate`` body ``{"prompt": [ids...], "max_new_tokens"?: n,
  "eos"?: id, "timeout_ms"?: ms}`` → ``{"tokens": [ids...]}`` — the
  autoregressive decode plane (serving/decode/); 503 until a
  ``DecodeScheduler`` is attached (constructor ``decoder=`` or
  ``attach_decoder()``).
- ``GET /healthz`` → queue depth, compiled buckets, drain state.
- ``GET /varz`` → the live telemetry registry snapshot (every counter /
  gauge / histogram, JSON) — inspect a running server without
  restarting it.
- ``GET /tracez`` → the flight recorder's recent completed spans plus
  currently-open spans (tracing.py ring buffer; empty lists when
  ``MXNET_TRACE`` is off).
- ``GET /metrics`` → the same registry in Prometheus text exposition
  format (clustermon.prometheus_text: ``# TYPE`` lines, rank label on
  every sample) — point a scrape config at the serving port directly.
- ``GET /incidents`` → clustermon incident history (open + recent
  closed straggler incidents with per-cause counts, JSON; empty shape
  when no aggregator runs in this process).
- ``GET /slo`` → the serving SLO view (slo.py): declared objectives,
  sliding-window latency percentiles, multi-window burn rates,
  saturation attribution, burning incident if any.
- ``GET /requestz`` → the bounded ring of the N slowest requests
  served, each with its request id and latency decomposition
  (``?limit=`` caps the list).

Error mapping: admission shape reject → 400, queue full (load shed) →
429, request deadline → 504, draining/closed → 503.  ``stop()`` is
drain-aware: admission closes first, every admitted response is
delivered, then the HTTP listener (if any) shuts down.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Optional

import numpy as onp

from .. import telemetry, tracing
from ..base import MXNetError
from .batcher import DynamicBatcher
from .engine import (BadRequestError, InferenceEngine, QueueFullError,
                     RequestTimeoutError, ServingClosedError)

__all__ = ["ServingServer"]


class ServingServer:
    """Serve a Block (or a prebuilt :class:`InferenceEngine`) behind a
    :class:`DynamicBatcher`.  ``engine_args`` / ``batcher_args`` pass
    through to the respective constructors."""

    def __init__(self, block_or_engine, engine_args: Optional[dict] = None,
                 batcher_args: Optional[dict] = None,
                 decoder=None, start: bool = True):
        if isinstance(block_or_engine, InferenceEngine):
            self.engine = block_or_engine
        else:
            self.engine = InferenceEngine(block_or_engine,
                                          **(engine_args or {}))
        self.batcher = DynamicBatcher(self.engine, start=start,
                                      **(batcher_args or {}))
        self.decoder = decoder        # DecodeScheduler (or None)
        self._httpd = None
        self._http_thread = None

    def attach_decoder(self, scheduler) -> "ServingServer":
        """Attach a ``DecodeScheduler`` so ``generate()`` and
        ``POST /generate`` serve autoregressive requests alongside
        ``predict()``."""
        self.decoder = scheduler
        return self

    # -- in-process API ------------------------------------------------------

    def predict(self, x, timeout_ms: Optional[float] = None):
        """Submit one example and block for its result (host numpy).
        ``timeout_ms`` bounds queue wait AND response wait."""
        fut = self.batcher.submit(x, timeout_ms=timeout_ms)
        # the dispatch itself runs after the deadline check, so give the
        # future a grace window beyond the request deadline
        wait = timeout_ms / 1e3 + 30.0 if timeout_ms is not None else None
        return fut.result(wait)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos: Optional[int] = None,
                 timeout_ms: Optional[float] = None):
        """Submit one generation request to the attached
        ``DecodeScheduler`` and block for the generated token list.
        Raises :class:`ServingClosedError` when no decoder is
        attached."""
        if self.decoder is None:
            raise ServingClosedError(
                "no decode scheduler attached to this server")
        fut = self.decoder.submit(prompt, max_new_tokens=max_new_tokens,
                                  eos=eos, timeout_ms=timeout_ms)
        wait = timeout_ms / 1e3 + 30.0 if timeout_ms is not None else None
        return fut.result(wait)

    def warmup(self, specs):
        return self.engine.warmup(specs)

    def healthz(self) -> dict:
        """Liveness + readiness: beyond drain state, load balancers get
        warmed-bucket count, queue saturation (depth / capacity) and
        open serving-incident count, so live-but-saturated is
        distinguishable from healthy."""
        from . import slo
        depth = self.batcher.pending()
        limit = self.batcher.queue_depth
        buckets = self.engine.buckets()
        open_serving = 0
        burning = None
        s = slo.get()
        if s is not None:
            open_serving = len(s.snapshot(1)["open"])
            burning = slo.burning_cause()
        h = {
            "status": "draining" if self.batcher.closed else "serving",
            "queue_depth": depth,
            "buckets": buckets,
            "max_batch_size": self.batcher.max_batch_size,
            "max_delay_ms": self.batcher.max_delay_ms,
            "queue_depth_limit": limit,
            "warmed_buckets": len(buckets),
            "queue_saturation": round(depth / limit, 4) if limit else 0.0,
            "open_serving_incidents": open_serving,
            "ready": (not self.batcher.closed and depth < limit
                      and open_serving == 0),
        }
        if burning:
            h["slo_burning"] = burning
        return h

    def varz(self) -> dict:
        """Live telemetry registry snapshot (what ``GET /varz``
        serves) — the same plain-data view ``telemetry.snapshot()``
        returns, so numbers reconcile with profiler.counters()."""
        return telemetry.snapshot()

    def tracez(self, limit: int = 100) -> dict:
        """Flight-recorder view (what ``GET /tracez`` serves): recent
        completed spans + currently-open spans."""
        return {"enabled": tracing.enabled(),
                "spans": tracing.span_count(),
                "dropped": tracing.dropped_count(),
                "recent": tracing.recent(limit),
                "open": tracing.open_spans()}

    def metricz(self) -> str:
        """Prometheus text exposition of the registry (what
        ``GET /metrics`` serves) — same numbers as /varz, scrapeable."""
        from .. import clustermon
        return clustermon.prometheus_text()

    def incidentz(self) -> dict:
        """Cluster incident history (what ``GET /incidents`` serves):
        open + recent closed incidents and per-cause counts from the
        rank-0 aggregator's incident store; the empty shape when no
        aggregator runs in this process."""
        from .. import clustermon
        return clustermon.incident_view()

    def sloz(self) -> dict:
        """Serving SLO view (what ``GET /slo`` serves): declared
        objectives, sliding-window percentiles, burn rates, saturation
        attribution and any burning incident — ``{"declared": false}``
        shape when no objectives are declared.  Forces a fresh
        evaluation so a burn clears even after traffic stops."""
        from . import slo
        return slo.slo_view()

    def requestz(self, limit: Optional[int] = None) -> dict:
        """Slowest-request ring (what ``GET /requestz`` serves): the N
        slowest requests served with their per-request latency
        decomposition, slowest first."""
        from . import slo
        return slo.requestz(limit)

    def stop(self, drain: bool = True):
        """Drain-aware shutdown: close admission (delivering admitted
        responses when ``drain``), then stop the HTTP listener."""
        self.batcher.close(drain=drain)
        if self.decoder is not None and not self.decoder.closed:
            self.decoder.close(drain=drain)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            if self._http_thread is not None:
                self._http_thread.join(10.0)
            self._httpd = self._http_thread = None

    # -- HTTP shim -----------------------------------------------------------

    def start_http(self, host: str = "127.0.0.1", port: int = 0):
        """Start the JSON endpoint on a daemon thread; returns
        ``(host, port)`` with the OS-assigned port when ``port=0``."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_text(self, code: int, text: str, ctype: str):
                body = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, server.healthz())
                elif self.path.split("?", 1)[0] == "/metrics":
                    self._reply_text(
                        200, server.metricz(),
                        "text/plain; version=0.0.4; charset=utf-8")
                elif self.path == "/varz":
                    self._reply(200, server.varz())
                elif self.path.split("?", 1)[0] == "/incidents":
                    self._reply(200, server.incidentz())
                elif self.path.split("?", 1)[0] == "/slo":
                    self._reply(200, server.sloz())
                elif self.path.split("?", 1)[0] == "/requestz":
                    limit = None
                    if "?" in self.path:
                        from urllib.parse import parse_qs
                        q = parse_qs(self.path.split("?", 1)[1])
                        try:
                            limit = int(q.get("limit", [None])[0])
                        except (TypeError, ValueError):
                            pass
                    self._reply(200, server.requestz(limit))
                elif self.path.split("?", 1)[0] == "/tracez":
                    limit = 100
                    if "?" in self.path:
                        from urllib.parse import parse_qs
                        q = parse_qs(self.path.split("?", 1)[1])
                        try:
                            limit = int(q.get("limit", ["100"])[0])
                        except ValueError:
                            pass
                    self._reply(200, server.tracez(limit))
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/generate":
                    self._generate()
                    return
                if self.path != "/predict":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    dtype = req.get("dtype") or server.engine.dtype \
                        or "float32"
                    x = onp.asarray(req["data"], dtype=dtype)
                except (KeyError, TypeError, ValueError) as e:
                    self._reply(400, {"error": f"bad request body: {e}"})
                    return
                try:
                    out = server.predict(x, timeout_ms=req.get("timeout_ms"))
                except BadRequestError as e:
                    self._reply(400, {"error": str(e)})
                except QueueFullError as e:
                    self._reply(429, {"error": str(e)})
                except RequestTimeoutError as e:
                    self._reply(504, {"error": str(e)})
                except ServingClosedError as e:
                    self._reply(503, {"error": str(e)})
                except MXNetError as e:
                    self._reply(500, {"error": str(e)})
                else:
                    if isinstance(out, (list, tuple)):
                        self._reply(200, {"outputs":
                                          [onp.asarray(o).tolist()
                                           for o in out]})
                    else:
                        self._reply(200, {"output":
                                          onp.asarray(out).tolist()})

            def _generate(self):
                """POST /generate body ``{"prompt": [ids...],
                "max_new_tokens"?: n, "eos"?: id, "timeout_ms"?: ms}``
                → ``{"tokens": [ids...]}`` (same error mapping as
                /predict; 503 when no decoder is attached)."""
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    prompt = [int(t) for t in req["prompt"]]
                    max_new = req.get("max_new_tokens")
                    eos = req.get("eos")
                except (KeyError, TypeError, ValueError) as e:
                    self._reply(400, {"error": f"bad request body: {e}"})
                    return
                try:
                    toks = server.generate(
                        prompt, max_new_tokens=max_new, eos=eos,
                        timeout_ms=req.get("timeout_ms"))
                except BadRequestError as e:
                    self._reply(400, {"error": str(e)})
                except QueueFullError as e:
                    self._reply(429, {"error": str(e)})
                except RequestTimeoutError as e:
                    self._reply(504, {"error": str(e)})
                except ServingClosedError as e:
                    self._reply(503, {"error": str(e)})
                except MXNetError as e:
                    self._reply(500, {"error": str(e)})
                else:
                    self._reply(200, {"tokens": [int(t) for t in toks]})

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="mxnet-serving-http",
            daemon=True)
        self._http_thread.start()
        return self._httpd.server_address

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False
