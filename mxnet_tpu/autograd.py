"""Imperative autograd: record/replay tape over ``jax.vjp``.

TPU-native re-expression of the reference's autograd
(``src/imperative/imperative.cc:204 RecordOp``, ``:377 Backward``;
Python surface ``python/mxnet/autograd.py:120-513``).  While recording,
every op invocation appends an ``_OpRecord`` (the op's pure jax function,
its input arrays, and graph nodes for inputs/outputs).  ``backward``
walks the tape in reverse, computing per-op cotangents with ``jax.vjp``
(forward is rematerialized — the TPU-friendly trade of FLOPs for HBM),
honoring ``grad_req`` write/add/null semantics (parity: OpReqType
kWriteTo/kAddTo, include/mxnet/op_attr_types.h:46-58).

``create_graph=True`` records every backward vjp as a tape op with node
linkage back to the forward inputs, so second-order gradients work
(parity: tests/python/unittest/test_higher_order_grad.py).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import MXNetError
from . import telemetry
from .imperative import cached_step as _cached_step

# every real vjp executable dispatch ticks the unified dispatch counter
# (see imperative/cached_step.py — the observable behind 1-dispatch/step)
_DISPATCH_CT = telemetry.counter("dispatch.count")

__all__ = [
    "record", "pause", "train_mode", "predict_mode",
    "is_recording", "is_training", "set_recording", "set_training",
    "mark_variables", "backward", "grad", "Function",
]

# --------------------------------------------------------------------------
# thread-local recording state (parity: Imperative thread-local is_train /
# is_recording flags, include/mxnet/imperative.h)
# --------------------------------------------------------------------------

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
    if not hasattr(_state, "grad_ready_hook"):
        _state.grad_ready_hook = None
    return _state


def set_grad_ready_hook(hook) -> None:
    """Install (or clear, with None) a per-parameter grad-ready hook:
    ``hook(grad_buffer)`` fires DURING backward the moment a parameter's
    gradient is final, before later (earlier-layer) vjps dispatch — the
    enabler for P3-style comm/compute overlap (p3store_dist.h:44-85):
    an async collective issued from the hook interleaves with the rest
    of the backward stream."""
    _st().grad_ready_hook = hook


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(flag: bool) -> bool:
    st = _st()
    old, st.recording = st.recording, bool(flag)
    return old


def set_training(flag: bool) -> bool:
    st = _st()
    old, st.training = st.training, bool(flag)
    return old


class _Scope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training

    def __enter__(self):
        if self._rec is not None:
            self._old_rec = set_recording(self._rec)
            if self._rec is True and not self._old_rec:
                # outermost record() scope: the cached-step capture
                # (imperative/cached_step.py) observes — or defers —
                # the training step starting here
                _cached_step.note_record_enter()
        if self._train is not None:
            self._old_train = set_training(self._train)
        return self

    def __exit__(self, *exc):
        if self._rec is not None:
            set_recording(self._old_rec)
        if self._train is not None:
            set_training(self._old_train)
        return False


def record(train_mode: bool = True) -> _Scope:
    """``with autograd.record():`` — turn on recording (+train mode)."""
    return _Scope(True, train_mode)


def pause(train_mode: bool = False) -> _Scope:
    """``with autograd.pause():`` — turn off recording inside a record scope."""
    return _Scope(False, train_mode)


def train_mode() -> _Scope:
    return _Scope(None, True)


def predict_mode() -> _Scope:
    return _Scope(None, False)


# --------------------------------------------------------------------------
# tape structure
# --------------------------------------------------------------------------

class _Node:
    """One version of an NDArray in the autograd graph (parity: AGInfo,
    include/mxnet/imperative.h:53)."""

    __slots__ = ("grad_array", "grad_req", "out_grad", "producer", "__weakref__")

    def __init__(self):
        self.grad_array = None      # NDArray sink (set by attach_grad)
        self.grad_req = "null"
        self.out_grad = None        # cotangent: jax array, or NDArray if create_graph
        self.producer = None        # _OpRecord that produced this node


class _OpRecord:
    __slots__ = ("fn", "saved_inputs", "in_nodes", "out_nodes", "multi_out",
                 "consumed", "out_specs", "sparse_bwd")

    def __init__(self, fn, saved_inputs, in_nodes, out_nodes, multi_out,
                 out_specs=None, sparse_bwd=None):
        self.fn = fn
        self.saved_inputs = saved_inputs
        self.in_nodes = in_nodes
        self.out_nodes = out_nodes
        self.multi_out = multi_out
        self.consumed = False
        self.out_specs = out_specs    # [(shape, dtype)] of the outputs
        # optional op-provided backward producing row_sparse cotangents
        # (parity: backward storage inference — SparseEmbeddingOpBackward)
        self.sparse_bwd = sparse_bwd


def _tape() -> List[_OpRecord]:
    return _st().tape


def _record(fn, in_nodes, saved_inputs, out_nodes, multi_out,
            out_specs=None, sparse_bwd=None):
    rec = _OpRecord(fn, saved_inputs, in_nodes, out_nodes, multi_out,
                    out_specs, sparse_bwd)
    for n in out_nodes:
        n.producer = rec
    _tape().append(rec)
    return rec


def record_apply(fn: Callable, nd_inputs: Sequence[Any], nd_outputs: Sequence[Any],
                 multi_out: bool, sparse_bwd=None) -> None:
    """Append one executed op to the tape.

    ``fn(*arrays)`` must be the pure jax function that produced
    ``nd_outputs``'s arrays from ``nd_inputs``'s arrays.  Called by the op
    registry when recording is on (parity: Imperative::RecordOp).
    """
    _record(fn, [x._ensure_node() for x in nd_inputs],
            [x._data for x in nd_inputs],
            [o._new_node() for o in nd_outputs], multi_out,
            out_specs=[(o.shape, o.dtype) for o in nd_outputs],
            sparse_bwd=sparse_bwd)


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Attach gradient buffers to arrays (parity: autograd.mark_variables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        node = var._ensure_node()
        node.grad_array = g
        node.grad_req = req


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _ct_data(g):
    """Raw jax array of a cotangent that may be an NDArray."""
    return g._data if hasattr(g, "_data") else g


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True, create_graph: bool = False,
             _collect_nodes=None):
    """Run backward from ``heads`` (parity: Imperative::Backward,
    python/mxnet/autograd.py:244).  ``_collect_nodes`` is the internal
    channel used by :func:`grad` to read cotangents of specific nodes."""
    from .ndarray import NDArray  # late import (cycle)

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    # A deferring cached step absorbs the backward into its capture
    # (or materializes and falls through to the real one below).
    if _cached_step._ACTIVE and _cached_step.deferred_backward(
            heads, head_grads, retain_graph, train_mode, create_graph,
            _collect_nodes):
        return None

    # Seed output cotangents.
    head_nodes = []
    for h, hg in zip(heads, head_grads):
        node = getattr(h, "_node", None)
        if node is None:
            continue
        seed = jnp.ones(h.shape, h.dtype) if hg is None else hg._data
        if create_graph:
            seed = NDArray(seed) if hg is None else hg
        _accumulate(node, seed, create_graph)
        head_nodes.append(node)
    if not head_nodes:
        raise MXNetError("backward: none of the heads is in a recorded graph; "
                         "run the computation inside autograd.record()")

    tape = _tape()
    # Mark the subgraph reachable backwards from heads.
    needed = set()
    frontier = list(head_nodes)
    seen_nodes = set()
    while frontier:
        node = frontier.pop()
        if id(node) in seen_nodes:
            continue
        seen_nodes.add(id(node))
        rec = node.producer
        if rec is not None and id(rec) not in needed:
            needed.add(id(rec))
            frontier.extend(rec.in_nodes)

    # P3-style overlap (parity: p3store_dist.h:44-85 priority pushes
    # overlapping backprop): when a grad-ready hook is installed, count
    # each grad-buffered node's pending consumer records; the moment the
    # last one runs, deliver the grad into its buffer EARLY and fire the
    # hook — the hook's async dispatch (e.g. a per-layer allreduce)
    # then interleaves with the remaining backward ops.
    hook = _st().grad_ready_hook
    pending: dict = {}
    delivered: set = set()
    if hook is not None:
        for rec in tape:
            if id(rec) not in needed:
                continue
            for n in rec.in_nodes:
                if n.grad_array is not None and n.grad_req != "null":
                    pending[id(n)] = pending.get(id(n), 0) + 1

    touched = list(head_nodes)
    with _Scope(None, train_mode):
        for rec in reversed(tape):
            if id(rec) not in needed:
                continue
            out_grads = [n.out_grad for n in rec.out_nodes]
            if all(g is None for g in out_grads):
                continue
            _apply_vjp(rec, out_grads, create_graph)
            touched.extend(rec.in_nodes)
            touched.extend(rec.out_nodes)
            if hook is not None:
                for n in rec.in_nodes:
                    k = id(n)
                    if k in pending:
                        pending[k] -= 1
                        if pending[k] == 0 and n.out_grad is not None \
                                and k not in delivered:
                            _deliver_grad(n)
                            delivered.add(k)
                            # recording OFF around the hook: its ops
                            # (slices/collectives) must not land on
                            # the live tape
                            with _Scope(False, None):
                                hook(n.grad_array)
            if not retain_graph:
                rec.consumed = True

    # Hand requested cotangents to grad() before they are cleared.
    collected = None
    if _collect_nodes is not None:
        collected = [n.out_grad for n in _collect_nodes]

    # Deliver accumulated grads into attached buffers (write/add semantics),
    # then clear cotangents — grads persist only in grad buffers, matching
    # the reference (AGInfo out_grads freed after Backward).
    seen = set(delivered)
    for node in touched:
        if id(node) in seen:
            continue
        seen.add(id(node))
        _deliver_grad(node)
    for node in touched:
        node.out_grad = None

    # expose the completed eager step to the cached-step observer so
    # Trainer.step can arm a capture for the next iteration
    if not create_graph and _collect_nodes is None:
        _cached_step.note_backward(tape, heads, head_grads, train_mode,
                                   retain_graph)

    if not retain_graph:
        _st().tape = [r for r in tape if not r.consumed]
    return collected


# jitted-backward cache: ((stable fn, n_in, multi_out, env), avals) →
# (_JitEntry, bwd).  Keyed on the op registry's cached partials
# (registry._STABLE_FNS), whose identity persists across steps — so the
# vjp of each op traces/compiles once PER INPUT SIGNATURE and every
# _OpRecord with the same (fn, avals) — e.g. 32 identical Dense layers —
# replays the SAME compiled transpose (forward is rematerialized
# *inside* the compiled program: FLOPs-for-HBM trade without per-step
# retracing).  The family table bounds distinct avals per fn at
# registry._MAX_JIT_SIGS; signatures beyond the cap run the eager vjp
# WITHOUT latching, so already-compiled signatures keep replaying
# compiled (the old per-family _JitEntry demoted the whole fn to eager
# forever once its sig budget overflowed).  The key owns the fn, so no
# id-reuse hazard.
_BWD_JIT: dict = {}
_BWD_FAMS: dict = {}    # family → set of avals granted a compile slot


def _make_bwd(fn, n_in, multi):
    """The one vjp-replay closure (args = saved inputs ++ cotangents),
    shared by the eager and jitted backward paths so they can't
    diverge."""
    def bwd(*args):
        out, vjp_fn = jax.vjp(fn, *args[:n_in])
        cts = args[n_in:]
        if multi:
            # cotangents must match the primal output's pytree exactly
            # (some multi-out ops return lists, others tuples)
            ct = jax.tree.unflatten(jax.tree.structure(out), list(cts))
        else:
            ct = cts[0]
        return vjp_fn(ct)

    return bwd


def _get_jitted_bwd(rec: _OpRecord):
    from .ops import registry

    fn = rec.fn
    if fn not in registry._STABLE_FNS and \
            not getattr(fn, "_mx_stable_fn", False):
        return None
    # env-numerics participates in the key: a no-params op caches the bare
    # op.fn under both env settings, so fn identity alone would replay a
    # backward traced under the other setting
    fam = (fn, len(rec.saved_inputs), rec.multi_out,
           registry._env_numerics_key())
    try:
        avals = tuple((tuple(a.shape), str(a.dtype))
                      for a in rec.saved_inputs)
    except Exception:       # shape-less saved input (sparse container)
        return None
    cached = _BWD_JIT.get((fam, avals))
    if cached is None:
        seen = _BWD_FAMS.setdefault(fam, set())
        if avals not in seen:
            if len(seen) >= registry._MAX_JIT_SIGS:
                return None         # over budget: eager vjp, no latch
            seen.add(avals)
        bwd = _make_bwd(fn, len(rec.saved_inputs), rec.multi_out)
        # artifact-store key: the forward partial's stable identity
        # stands in for the fn object (which only ids this process)
        akey = getattr(fn, "_mx_akey", None)
        jakey = (("bwd", akey, len(rec.saved_inputs), bool(rec.multi_out),
                  registry._env_numerics_key())
                 if akey is not None else None)
        cached = _BWD_JIT[(fam, avals)] = (registry._JitEntry(
            bwd, akey=jakey), bwd)
    return cached


def _apply_vjp(rec: _OpRecord, out_grads, create_graph: bool):
    """Compute input cotangents for one record and accumulate into in_nodes."""
    from .ndarray import NDArray

    _DISPATCH_CT.inc()
    fn, saved = rec.fn, rec.saved_inputs

    if rec.sparse_bwd is not None and not create_graph:
        # op supplies its own backward emitting row_sparse cotangents
        # at nnz cost (never materializing the dense vocab-sized grad)
        cts = [None if g is None else _ct_data(g) for g in out_grads]
        grads = rec.sparse_bwd(saved, cts)
        for node, g in zip(rec.in_nodes, grads):
            if g is not None:
                _accumulate(node, g, False)
        return

    out_specs = rec.out_specs
    filled = []
    for i, g in enumerate(out_grads):
        if g is None:
            if out_specs is None:
                specs = jax.eval_shape(fn, *saved)
                if not rec.multi_out:
                    specs = (specs,)
                out_specs = [(s.shape, s.dtype) for s in specs]
            z = jnp.zeros(*out_specs[i])
            filled.append(NDArray(z) if create_graph else z)
        else:
            filled.append(g)

    n_in = len(saved)
    bwd = _make_bwd(fn, n_in, rec.multi_out)

    if create_graph:
        ct_nodes = [g._ensure_node() for g in filled]
        args = list(saved) + [g._data for g in filled]
        with _Scope(False, None):
            out_arrays = bwd(*args)
        out_nd = [NDArray(a) for a in out_arrays]
        _record(bwd, list(rec.in_nodes) + ct_nodes, args,
                [o._new_node() for o in out_nd], True)
        for node, nd in zip(rec.in_nodes, out_nd):
            _accumulate(node, nd, True)
    else:
        args = [*saved, *[_ct_data(g) for g in filled]]
        cached = _get_jitted_bwd(rec)
        if cached is not None:
            jentry, eager_bwd = cached
            grads = jentry.run(eager_bwd, args)
        else:
            grads = bwd(*args)
        for node, g in zip(rec.in_nodes, grads):
            _accumulate(node, g, False)


def _accumulate(node: _Node, g, create_graph: bool):
    if node.out_grad is None:
        node.out_grad = g
    elif create_graph:
        node.out_grad = _recorded_add(node.out_grad, g)
    else:
        node.out_grad = _ct_sum(node.out_grad, g)


def _deliver_grad(node: _Node) -> None:
    """Write a node's accumulated cotangent into its attached grad
    buffer honoring grad_req (write/add) and row_sparse buffers."""
    if node.grad_array is None or node.out_grad is None \
            or node.grad_req == "null":
        return
    from .ndarray.sparse import RowSparseNDArray, merge
    buf = node.grad_array
    og = node.out_grad
    if isinstance(buf, RowSparseNDArray):
        # grad_stype='row_sparse' buffer: keep grads sparse
        if not isinstance(og, RowSparseNDArray):
            raise MXNetError(
                "parameter has grad_stype='row_sparse' but a dense "
                "gradient flowed into it; only ops with a sparse "
                "backward (Embedding(sparse_grad=True)) may feed a "
                "row_sparse grad buffer")
        if node.grad_req == "add" and buf.nnz:
            og = merge(buf, og)
        buf.data, buf.indices = og.data, og.indices
    else:
        if isinstance(og, RowSparseNDArray):
            og = og.todense()
        g = _ct_data(og)
        if node.grad_req == "add":
            buf._data = buf._data + g
        else:
            buf._data = g


def _ct_sum(a, b):
    """Sum two cotangents, either of which may be row_sparse."""
    from .ndarray.sparse import RowSparseNDArray, merge
    a_sp = isinstance(a, RowSparseNDArray)
    b_sp = isinstance(b, RowSparseNDArray)
    if a_sp and b_sp:
        return merge(a, b)
    if a_sp:
        return a.todense()._data + b
    if b_sp:
        return a + b.todense()._data
    return a + b


def _recorded_add(a, b):
    """a + b where both are NDArrays, recorded on the tape for 2nd order."""
    from .ndarray import NDArray

    fn = lambda x, y: x + y
    out = NDArray(a._data + b._data)
    _record(fn, [a._ensure_node(), b._ensure_node()], [a._data, b._data],
            [out._new_node()], False)
    return out


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Return gradients of heads w.r.t. variables without touching ``.grad``
    buffers (parity: autograd.grad, python/mxnet/autograd.py:303)."""
    from .ndarray import NDArray

    single = isinstance(variables, NDArray)
    if isinstance(heads, NDArray):
        heads = [heads]
    if single:
        variables = [variables]
    if retain_graph is None:
        retain_graph = create_graph

    var_nodes = [v._ensure_node() for v in variables]
    saved = [(n.grad_array, n.grad_req, n.out_grad) for n in var_nodes]
    for n in var_nodes:
        n.grad_array, n.grad_req, n.out_grad = None, "null", None

    collected = backward(heads, head_grads, retain_graph=retain_graph,
                         train_mode=train_mode, create_graph=create_graph,
                         _collect_nodes=var_nodes)

    results = []
    for v, n, g, (ga, gr, og) in zip(variables, var_nodes, collected, saved):
        if g is None:
            raise MXNetError("one of the variables is not differentiably "
                             "connected to the heads")
        from .ndarray.sparse import RowSparseNDArray
        if isinstance(g, (NDArray, RowSparseNDArray)):
            out = g  # row_sparse cotangents pass through as containers
        else:
            out = NDArray(g)
        results.append(out)
        n.grad_array, n.grad_req, n.out_grad = ga, gr, og
    return results if not single else results[0] if len(results) == 1 else results


# --------------------------------------------------------------------------
# custom Function (parity: mx.autograd.Function, autograd.py:399-513)
# --------------------------------------------------------------------------

class Function:
    """User-defined differentiable function.

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)``; call the instance on NDArrays.
    Parity: python/mxnet/autograd.py:399 (Function), executed in the
    reference by the custom-op worker pool (src/operator/custom/).
    """

    def __init__(self):
        self.saved_tensors = ()

    def save_for_backward(self, *args):
        self.saved_tensors = args

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray

        with _Scope(False, None):
            outputs = self.forward(*inputs)
        multi = isinstance(outputs, (list, tuple))
        outs = list(outputs) if multi else [outputs]

        if is_recording():
            func = self

            def run_fwd(*arrays):
                nd_in = [NDArray(a) for a in arrays]
                with _Scope(False, None):
                    o = func.forward(*nd_in)
                o = o if isinstance(o, (list, tuple)) else [o]
                res = tuple(x._data for x in o)
                return res if multi else res[0]

            @jax.custom_vjp
            def fn_cv(*arrays):
                return run_fwd(*arrays)

            def fn_fwd(*arrays):
                return run_fwd(*arrays), None

            def fn_bwd(res, cts):
                nd_cts = [NDArray(c) for c in (cts if multi else (cts,))]
                with _Scope(False, None):
                    gin = func.backward(*nd_cts)
                gin = gin if isinstance(gin, (list, tuple)) else [gin]
                return tuple(g._data for g in gin)

            fn_cv.defvjp(fn_fwd, fn_bwd)
            record_apply(fn_cv, list(inputs), outs, multi_out=multi)
        return outputs
