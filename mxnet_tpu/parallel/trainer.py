"""SPMDTrainer: one fully-compiled, mesh-partitioned training step.

This is the TPU-native fast path that subsumes the reference's
KVStore+engine pipeline (SURVEY.md §3.4): forward, backward, gradient
all-reduce, and the optimizer update are one XLA executable; GSPMD
inserts the ICI collectives that `CommDevice`/NCCL provided.  Gluon's
eager Trainer remains for API parity; benchmarks and multi-chip training
use this.

Design notes:
- params stay replicated (pure DP) or follow per-parameter
  PartitionSpecs (TP/SP) set via ``Parameter.shard``.
- batch tensors are sharded on the 'dp' mesh axis.
- optimizer state lives as a pytree of arrays, donated every step
  (buffer donation == the reference's in-place update kernels).
- BatchNorm moving stats ride the trace-context aux mechanism and are
  folded back after each step.
"""
from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as onp
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import telemetry
from .. import tracing
from ..base import MXNetError
from ..ndarray import NDArray
from .. import autograd as ag
from ..gluon.block import _TraceContext, _trace_scope
from ..ops import registry as _reg
from ..ops.random import next_key
from .. import optimizer as opt_mod
from .mesh import default_mesh

__all__ = ["SPMDTrainer"]


class SPMDTrainer:
    def __init__(self, net, loss_fn: Callable, optimizer="sgd",
                 optimizer_params: Optional[dict] = None,
                 mesh: Optional[Mesh] = None, batch_axis: int = 0,
                 donate: bool = True, dtype: Optional[str] = None,
                 remat: bool = False, seq_axis: Optional[int] = None,
                 micro_batches: int = 1, zero_stage: Optional[int] = None,
                 data_transform: Optional[Callable] = None,
                 zero: Optional[int] = None):
        self.net = net
        self.loss_fn = loss_fn
        # device-side input preprocessing: a jittable fn applied to each
        # step's data INSIDE the compiled step.  Lets the input pipeline
        # ship compact dtypes (uint8 pixels at 1/4 the f32 bytes over
        # PCIe/ICI/tunnel) and do normalize/transpose on-chip, where it
        # fuses into the first conv.  (The reference bakes mean/std into
        # its C++ iter on the HOST — iter_image_recordio_2.cc normalize —
        # which quadruples the host->device transfer; on TPU the wire is
        # the scarce resource, so the transform belongs device-side.)
        self._data_transform = data_transform
        # ``mesh`` accepts a raw jax Mesh OR a parallel.mesh4d.MeshPlan
        # (the composed-axes front door); with neither, an exported
        # MXNET_MESH=dp2,tp2 lays out the run, else dp over all devices
        self.plan = None
        if mesh is not None and not isinstance(mesh, Mesh):
            self.plan = mesh
            mesh = mesh.mesh
        elif mesh is None:
            from .mesh4d import mesh_plan_from_env
            self.plan = mesh_plan_from_env()
            if self.plan is not None:
                mesh = self.plan.mesh
        self.mesh = mesh or default_mesh()
        self.batch_axis = batch_axis
        # sequence parallelism: shard this data axis over the mesh's
        # "sp" axis (ring attention inside the model exchanges K/V
        # between the sequence shards)
        self.seq_axis = seq_axis
        # rematerialization: recompute the forward during backward
        # instead of keeping activations live — trades FLOPs for HBM
        # (the jax.checkpoint knob the build targets for long-context /
        # big-batch training; the reference has no equivalent because
        # its engine frees activations eagerly per-op)
        self.remat = bool(remat)
        # gradient accumulation: split each step's batch into k
        # micro-batches scanned sequentially, averaging gradients —
        # activations live for one micro-batch at a time (the HBM lever
        # for big effective batches; composes with remat).  BatchNorm
        # batch statistics are per-micro-batch, like any accumulation
        # scheme's.
        if micro_batches < 1:
            raise MXNetError("micro_batches must be >= 1")
        self.micro_batches = int(micro_batches)
        # ZeRO-style memory sharding over the dp axis (the GSPMD
        # re-expression of the reference's server-held optimizer state,
        # kvstore_dist_server.h ApplyUpdates, and of ZeRO/FSDP):
        #   0 — off: params and optimizer state replicated across dp.
        #   1/2 — optimizer state sharded over dp; GSPMD turns the
        #       update into reduce-scatter(grad) -> sharded update ->
        #       all-gather(weight), so stages 1 and 2 coincide here.
        #   3 — FSDP: master params ALSO sharded over dp; each use in
        #       the forward all-gathers just-in-time.
        # Per-parameter TP shardings (Parameter.shard) take precedence;
        # tensors with no dp-divisible axis stay replicated.
        # ``zero=`` is the cross-funnel constructor knob (same name as
        # gluon.Trainer's); both default to MXNET_ZERO so `MXNET_ZERO=1`
        # turns on stage-1 sharding with no code change.
        if zero_stage is None:
            zero_stage = zero
        if zero_stage is None:
            from ..optimizer.fused_step import zero_enabled
            zero_stage = 1 if zero_enabled() else 0
        if zero_stage not in (0, 1, 2, 3):
            raise MXNetError("zero_stage must be 0, 1, 2 or 3")
        self.zero_stage = int(zero_stage)
        # mixed precision (parity: AMP bf16 — master weights stay f32,
        # forward/backward compute in bf16 on the MXU; bf16 needs no loss
        # scaling on TPU, SURVEY.md §7 stage 7)
        self.amp_dtype = (jnp.bfloat16
                          if dtype in ("bfloat16", "bf16", "float16")
                          else None)
        # the global AMP policy (amp.init / MXNET_AMP) reaches this
        # funnel too: compute dtype from the policy when the ctor did
        # not pin one, and a dynamic loss scaler whose state rides the
        # scan carry so a whole fused window still dispatches once
        from ..amp import policy as _amp_policy
        self._amp_scaler = None
        if _amp_policy.enabled():
            if self.amp_dtype is None:
                self.amp_dtype = jnp.dtype(_amp_policy.compute_dtype())
            from ..amp.loss_scaler import LossScaler
            init = (2.0 ** 16
                    if _amp_policy.compute_dtype_str() == "float16"
                    else 1.0)
            self._amp_scaler = LossScaler(init_scale=init)
        self.optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self._params = net.collect_params()
        self._pkeys = list(self._params.keys())
        for p in self._params.values():
            p._check_initialized()
        self._opt_state = {
            k: tuple(s._data for s in
                     self.optimizer.create_state(i, self._params[k].data()))
            for i, k in enumerate(self._pkeys)}
        self._step_cache: Dict[Any, Any] = {}
        self._donate = donate
        self.num_update = 0
        self._comm_model = None   # lazy (rs, ag, ar) analytic bytes/step

    # -- sharding ----------------------------------------------------------
    def _zero_spec(self, param):
        """PartitionSpec sharding ``param``'s largest dp-divisible axis
        over 'dp', or None when nothing divides (small biases etc. stay
        replicated — their memory is negligible)."""
        if "dp" not in self.mesh.axis_names:
            return None
        ndp = self.mesh.shape["dp"]
        if ndp <= 1:
            return None
        shape = param.shape
        best = None
        for ax, dim in enumerate(shape or ()):
            if dim % ndp == 0 and (best is None or dim > shape[best]):
                best = ax
        if best is None:
            return None
        spec = [None] * len(shape)
        spec[best] = "dp"
        return PartitionSpec(*spec)

    def _param_sharding(self, param):
        spec = param._sharding
        if spec is None and self.zero_stage >= 3:
            spec = self._zero_spec(param)
        return NamedSharding(self.mesh, spec or PartitionSpec())

    def _composed_zero_spec(self, param):
        """Compose the ZeRO dp-shard ONTO the param's existing spec:
        the largest still-unsharded dp-divisible axis takes 'dp', so a
        P(None, 'tp') row weight's optimizer state lands P('dp', 'tp')
        — 1/(dp·tp) per device, the 4-D composition rule.  Returns the
        spec unchanged when dp is absent/1, already used, or nothing
        divides."""
        spec = param._sharding
        if "dp" not in self.mesh.axis_names:
            return spec
        ndp = self.mesh.shape["dp"]
        if ndp <= 1:
            return spec
        shape = param.shape or ()
        base = list(spec) if spec is not None else []
        base += [None] * (len(shape) - len(base))
        for s in base:
            if s == "dp" or (isinstance(s, (tuple, list)) and "dp" in s):
                return spec
        best = None
        for ax, dim in enumerate(shape):
            if base[ax] is not None:
                continue            # already carries tp/pp/sp/ep
            if dim % ndp == 0 and (best is None or dim > shape[best]):
                best = ax
        if best is None:
            return spec
        base[best] = "dp"
        return PartitionSpec(*base)

    def _opt_state_sharding(self, param):
        """Optimizer-state sharding: follows the param (TP etc.), plus
        the ZeRO dp-shard composed onto whatever axes the param already
        carries."""
        spec = param._sharding
        if self.zero_stage >= 1:
            spec = self._composed_zero_spec(param)
        return NamedSharding(self.mesh, spec or PartitionSpec())

    def _batch_sharding(self, ndim):
        spec = [None] * ndim
        if "dp" in self.mesh.axis_names and self.batch_axis < ndim:
            spec[self.batch_axis] = "dp"
        if (self.seq_axis is not None and "sp" in self.mesh.axis_names
                and self.seq_axis < ndim
                and self.seq_axis != self.batch_axis):
            spec[self.seq_axis] = "sp"
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    # -- compiled step -----------------------------------------------------
    def _make_step_fn(self):
        """The raw (un-jitted) step function + its aux-discovery cell.

        Shared by the single-step jit and the fused multi-step scan
        (``run_steps``).  BatchNorm-style aux state (running stats) is
        folded into ``new_params`` so a device-side loop threads the
        updated stats into the next iteration."""
        net, loss_fn, opt = self.net, self.loss_fn, self.optimizer
        pkeys = self._pkeys
        params = [self._params[k] for k in pkeys]
        pindex = {id(p): i for i, p in enumerate(params)}
        cell = {"aux": []}

        amp = self.amp_dtype

        scaler = self._amp_scaler

        def step(key, lr, wd, p_arrays, opt_state, data, label,
                 amp_state=None):
            if self._data_transform is not None:
                data = self._data_transform(data)
            # traced loss scale: a dynamic-scale update never recompiles
            scale = amp_state[0] if scaler is not None else None

            def loss_of(p_list):
                tc = _TraceContext(key)
                saved = [p._data for p in params]
                if amp is not None:
                    p_list = [a.astype(amp) if jnp.issubdtype(
                        a.dtype, jnp.floating) else a for a in p_list]
                    d_in = data.astype(amp) if jnp.issubdtype(
                        data.dtype, jnp.floating) else data
                else:
                    d_in = data
                try:
                    for p, a in zip(params, p_list):
                        p._data = NDArray(a)
                    with _trace_scope(tc), ag.pause(train_mode=True):
                        out = net.forward(NDArray(d_in))
                        loss = loss_fn(out, NDArray(label))
                    cell["aux"] = list(tc.aux)
                    loss_mean = loss._data.astype(jnp.float32).mean()
                    if scale is not None:
                        # power-of-two multiply: exact for f32/bf16
                        loss_mean = loss_mean * scale
                    return loss_mean, tuple(v for _, v in tc.aux)
                finally:
                    for p, s in zip(params, saved):
                        p._data = s

            grad_target = (jax.checkpoint(loss_of) if self.remat
                           else loss_of)
            n_micro = self.micro_batches
            if n_micro == 1:
                (loss_val, aux), grads = jax.value_and_grad(
                    grad_target, has_aux=True)(list(p_arrays))
            else:
                saved_batch = (data, label)
                ba = self.batch_axis

                def split_mb(x):
                    # arrays of lower rank (e.g. (B,) labels beside
                    # time-major (T, B, F) data) batch on axis 0
                    ax = ba if ba < x.ndim else 0
                    if x.shape[ax] % n_micro:
                        raise MXNetError(
                            f"batch {x.shape[ax]} (axis {ax}) not "
                            f"divisible by micro_batches={n_micro}")
                    # micro chunks along the batch axis, scan dim in
                    # front
                    moved = jnp.moveaxis(x, ax, 0)
                    moved = moved.reshape(
                        (n_micro, moved.shape[0] // n_micro)
                        + moved.shape[1:])
                    return jnp.moveaxis(moved, 1, ax + 1)

                dmb = split_mb(data)
                lmb = split_mb(label)

                def micro(acc, mb):
                    d, l = mb
                    # rebind the closed-over batch for this micro-step
                    nonlocal data, label
                    data, label = d, l
                    (lv, aux), g = jax.value_and_grad(
                        grad_target, has_aux=True)(list(p_arrays))
                    acc = [a + gi for a, gi in zip(acc, g)]
                    return acc, (lv, aux)

                zero = [jnp.zeros(a.shape,
                                  a.dtype if jnp.issubdtype(
                                      a.dtype, jnp.floating)
                                  else jnp.float32)
                        for a in p_arrays]
                gsum, (losses, aux_stack) = jax.lax.scan(
                    micro, zero, (dmb, lmb))
                grads = [g / n_micro for g in gsum]
                loss_val = losses.mean()
                # BN-style aux keeps the LAST micro-batch's update
                aux = jax.tree_util.tree_map(lambda x: x[-1], aux_stack)
                data, label = saved_batch

            def do_update(p_in, g_in, s_in):
                new_params, new_state = [], []
                for k, w, g, st in zip(pkeys, p_in, g_in, s_in):
                    param = self._params[k]
                    if param.grad_req == "null":
                        new_params.append(w)
                        new_state.append(st)
                        continue
                    sp = dict(opt.static_params(0))
                    sp.setdefault("rescale_grad", 1.0)
                    sp.setdefault("clip_gradient",
                                  float(opt.clip_gradient)
                                  if opt.clip_gradient is not None else -1.0)
                    from ..optimizer.optimizer import _lowp_guard
                    fn = _lowp_guard(_reg.get(opt.op_name).fn)
                    eff_lr = lr * param.lr_mult
                    eff_wd = wd * param.wd_mult
                    if opt.uses_lr:
                        out = fn(w, g, *st, lr=eff_lr, wd=eff_wd, **sp)
                    else:
                        out = fn(w, g, *st, wd=eff_wd, **sp)
                    outs = out if isinstance(out, tuple) else (out,)
                    new_params.append(outs[0])
                    new_state.append(tuple(outs[1:]))
                return new_params, new_state

            amp_out = None
            if scaler is None:
                new_params, new_state = do_update(p_arrays, grads,
                                                  opt_state)
            else:
                good = amp_state[1]
                inv = 1.0 / scale
                loss_val = loss_val * inv
                grads = [g * inv.astype(g.dtype)
                         if jnp.issubdtype(g.dtype, jnp.floating) else g
                         for g in grads]
                finite = jnp.bool_(True)
                for g in grads:
                    if jnp.issubdtype(g.dtype, jnp.floating):
                        finite = jnp.logical_and(finite,
                                                 jnp.isfinite(g).all())
                # wire discipline: the gradient collective GSPMD inserts
                # rides next to this round-trip, so the dp ring carries
                # the policy storage dtype; masters update from the
                # dequantized value (checked BEFORE the cast — fp8 e4m3
                # has no inf and would fold overflow into NaN)
                from ..amp import policy as _amp_policy
                wire = jnp.dtype(_amp_policy.storage_dtype())
                grads = [g.astype(wire).astype(g.dtype)
                         if (jnp.issubdtype(g.dtype, jnp.floating)
                             and g.dtype.itemsize > wire.itemsize) else g
                         for g in grads]

                def _apply(opnds):
                    p_in, g_in, s_in = opnds
                    return do_update(p_in, g_in, s_in)

                def _skip(opnds):
                    p_in, _g, s_in = opnds
                    return list(p_in), [tuple(s) for s in s_in]

                new_params, new_state = jax.lax.cond(
                    finite, _apply, _skip,
                    (list(p_arrays), grads, list(opt_state)))
                factor = scaler._scale_factor
                window = scaler._scale_window
                good1 = good + 1.0
                grown = jnp.where(good1 >= window, scale * factor, scale)
                new_scale = jnp.where(
                    finite, grown,
                    jnp.maximum(scale * (1.0 / factor), 1.0))
                new_good = jnp.where(
                    finite, jnp.where(good1 >= window, 0.0, good1), 0.0)
                amp_out = (new_scale, new_good,
                           jnp.logical_not(finite).astype(jnp.float32))
            # fold traced aux updates (BN running stats) into new_params
            # so they flow through the step output — a scanned step sees
            # iteration i's stats at iteration i+1
            for (pobj, _), v in zip(cell["aux"], aux):
                idx = pindex.get(id(pobj))
                if idx is not None:
                    new_params[idx] = v.astype(p_arrays[idx].dtype)
            if scaler is not None:
                return new_params, new_state, loss_val, aux, amp_out
            return new_params, new_state, loss_val, aux

        return step, cell, params

    def _state_shardings(self, params):
        p_shardings = [self._param_sharding(p) for p in params]
        s_shardings = [tuple(self._opt_state_sharding(p) for _ in st)
                       for p, st in zip(
                           params,
                           (self._opt_state[k] for k in self._pkeys))]
        return p_shardings, s_shardings

    def _build_step(self, data_shape, data_dtype, label_shape, label_dtype):
        step, cell, params = self._make_step_fn()
        p_shardings, s_shardings = self._state_shardings(params)
        rep = NamedSharding(self.mesh, PartitionSpec())
        in_shardings = (rep, rep, rep, p_shardings, s_shardings,
                        self._batch_sharding(len(data_shape)),
                        self._batch_sharding(len(label_shape)))
        donate = (3, 4) if self._donate else ()
        # pin outputs to the declared state shardings: without this,
        # GSPMD may hand back e.g. a bias sharded like the matmul it
        # feeds, and the next call's replicated in_sharding rejects it
        out_shardings = (p_shardings, s_shardings, rep, rep)
        if self._amp_scaler is not None:
            in_shardings = in_shardings + (rep,)
            out_shardings = out_shardings + (rep,)
        jitted = jax.jit(step, in_shardings=in_shardings,
                         out_shardings=out_shardings,
                         donate_argnums=donate)
        return jitted, cell

    # -- executable-artifact store (zero-compile restart) ------------------
    def _artifact_fp(self):
        """Content fingerprint of everything a compiled step bakes in
        beyond the (data, label) signature: model identity + parameter
        spec (shapes/dtypes/shardings/mults), optimizer statics, mesh
        geometry, and the trainer's compile-relevant knobs.  Part of
        every ``spmd_step`` artifact key, so a different model, mesh or
        optimizer can never replay this trainer's executables."""
        opt = self.optimizer
        try:
            statics = tuple(sorted(opt.static_params(0).items()))
        except Exception:
            statics = ()
        pspec = tuple(
            (k, tuple(self._params[k].data().shape),
             str(self._params[k].data().dtype),
             repr(self._params[k]._sharding),
             float(self._params[k].lr_mult),
             float(self._params[k].wd_mult),
             self._params[k].grad_req,
             tuple((tuple(a.shape), str(a.dtype))
                   for a in self._opt_state[k]))
            for k in self._pkeys)
        return (type(self.net).__name__,
                getattr(self.loss_fn, "__name__",
                        type(self.loss_fn).__name__),
                pspec, type(opt).__name__, opt.op_name, statics,
                repr(opt.clip_gradient),
                bool(self._donate), self.batch_axis, self.seq_axis,
                self.remat, self.micro_batches, self.zero_stage,
                str(self.amp_dtype), self._data_transform is not None,
                tuple(self.mesh.axis_names),
                tuple(int(self.mesh.shape[a])
                      for a in self.mesh.axis_names))

    def _resolve_exec(self, sig, jitted, cell, args):
        """First execution of a step signature: consult the executable-
        artifact store.  Hit → deserialize (no compile recorded;
        aux-param discovery re-runs as a compile-free abstract trace so
        ``cell`` matches what a real trace would have found).  Miss
        with the store on → AOT-compile here and commit.  Store off →
        keep the lazy jit wrapper (it compiles at first call, as
        before).  Returns ``(executable, compiled_now)``."""
        from .. import artifacts
        if not artifacts.enabled():
            return jitted, True
        asig = (self._artifact_fp(), sig)
        art = artifacts.load("spmd_step", asig)
        if art is not None:
            try:
                jitted.eval_shape(*args)    # trace-only: fills cell
            except Exception:
                pass
            self._step_cache[sig] = (art.compiled, cell)
            return art.compiled, False
        try:
            ex = jitted.lower(*args).compile()
        except Exception:
            # lowering declined (or AOT unsupported): the lazy wrapper
            # still works, the store just stays cold for this signature
            return jitted, True
        artifacts.save("spmd_step", asig, ex,
                       meta={"trainer_fp": repr(self._artifact_fp()),
                             "sig": sig})
        self._step_cache[sig] = (ex, cell)
        return ex, True

    def warm_start(self) -> int:
        """Drain every compatible ``spmd_step`` artifact into the step
        cache in ONE call, so a restarted trainer reaches its first
        ``step()``/``run_steps()`` with ``compile.count == 0``.  Only
        artifacts recorded under this trainer's exact fingerprint (and
        the store's own amp/jax/backend key) install; everything else
        is skipped silently.  Returns the number of executables
        installed."""
        from .. import artifacts
        if not artifacts.enabled():
            return 0
        fp = repr(self._artifact_fp())
        n = 0
        for art in artifacts.load_all("spmd_step"):
            sig = art.meta.get("sig")
            if art.meta.get("trainer_fp") != fp or sig is None \
                    or sig in self._step_cache:
                continue
            self._step_cache[sig] = (art.compiled, {"aux": []})
            n += 1
        if n:
            from ..log import get_logger
            get_logger("mxnet_tpu.parallel").info(
                "warm_start: %d step executable(s) loaded from %s",
                n, artifacts.store_dir())
        return n

    def _window_sharding(self, ndim):
        """Sharding for a (n_steps, batch, ...) window: the leading
        step axis is replicated, batch/seq axes shift right by one."""
        inner = self._batch_sharding(ndim - 1)
        return NamedSharding(self.mesh,
                             PartitionSpec(None, *inner.spec))

    def _build_multi(self, data_shape, data_dtype, label_shape, label_dtype,
                     n_steps, per_step_data=False):
        """Fused multi-step: ``n_steps`` full train steps inside ONE
        executable via lax.scan — the engine-bulking idea
        (MXNET_EXEC_BULK_EXEC_*, SURVEY.md §3.3) taken to its XLA-native
        limit.  One launch per n steps amortizes dispatch/launch
        latency; lr/wd are held fixed across the fused window.

        ``per_step_data``: data/label carry a leading ``n_steps`` axis
        and the scan consumes one batch per step — the data-fed window
        (input pipeline → device once per window, not per step)."""
        step, cell, params = self._make_step_fn()
        amp = self._amp_scaler is not None

        if amp:
            # the loss-scale pair rides the scan carry: the whole fused
            # window stays one executable, overflow steps inside it skip
            # their own update, and the skip count accumulates so the
            # scaler's host-side telemetry stays exact
            def many(key, lr, wd, p_arrays, opt_state, data, label,
                     amp_state):
                def body(carry, xs):
                    key, p, s, scale, good, nskip = carry
                    d, l = (data, label) if xs is None else xs
                    key, sub = jax.random.split(key)
                    new_p, new_s, loss, _aux, (ns, ng, sk) = step(
                        sub, lr, wd, p, s, d, l, (scale, good))
                    return (key, new_p, new_s, ns, ng, nskip + sk), loss
                carry0 = (key, list(p_arrays), list(opt_state),
                          amp_state[0], amp_state[1], jnp.float32(0.0))
                (key, p, s, scale, good, nskip), losses = jax.lax.scan(
                    body, carry0,
                    (data, label) if per_step_data else None,
                    length=None if per_step_data else n_steps)
                return p, s, losses, (scale, good, nskip)
        else:
            def many(key, lr, wd, p_arrays, opt_state, data, label):
                def body(carry, xs):
                    key, p, s = carry
                    d, l = (data, label) if xs is None else xs
                    key, sub = jax.random.split(key)
                    new_p, new_s, loss, _aux = step(sub, lr, wd, p, s,
                                                    d, l)
                    return (key, new_p, new_s), loss
                (key, p, s), losses = jax.lax.scan(
                    body, (key, list(p_arrays), list(opt_state)),
                    (data, label) if per_step_data else None,
                    length=None if per_step_data else n_steps)
                return p, s, losses

        p_shardings, s_shardings = self._state_shardings(params)
        rep = NamedSharding(self.mesh, PartitionSpec())
        shard_of = (self._window_sharding if per_step_data
                    else self._batch_sharding)
        in_shardings = (rep, rep, rep, p_shardings, s_shardings,
                        shard_of(len(data_shape)),
                        shard_of(len(label_shape)))
        out_shardings = (p_shardings, s_shardings, rep)
        if amp:
            in_shardings = in_shardings + (rep,)
            out_shardings = out_shardings + (rep,)
        donate = (3, 4) if self._donate else ()
        jitted = jax.jit(many, in_shardings=in_shardings,
                         out_shardings=out_shardings,
                         donate_argnums=donate)
        return jitted, cell

    @staticmethod
    def _put(arr, sharding):
        """Reshard ``arr`` onto ``sharding`` if it is committed
        elsewhere (an NDArray input is committed to one device at
        construction; jit with in_shardings rejects the mismatch
        rather than auto-resharding).  No-op when already placed."""
        cur = getattr(arr, "sharding", None)
        if cur == sharding or not getattr(arr, "_committed", False):
            return arr
        return jax.device_put(arr, sharding)

    def _stage_input(self, x, sharding):
        """Stage one batch tensor for the compiled step.  A batch the
        device-feed pipeline already committed under this trainer's
        sharding (data.DevicePrefetcher) passes through untouched — the
        step path performs NO transfer.  Host inputs (numpy/list) and
        mis-committed arrays pay an inline H2D/reshard here, accounted
        as ``input.step_h2d`` so the telemetry report can see the input
        pipeline sitting on the critical path."""
        if isinstance(x, NDArray):
            arr, was_host = x._data, False
        elif isinstance(x, jax.Array):
            arr, was_host = x, False
        else:
            arr, was_host = jnp.asarray(x), True
        out = self._put(arr, sharding)
        if was_host or out is not arr:
            telemetry.record_h2d_bytes(out.nbytes, step_path=True)
        return out

    def step(self, data, label, batch_size: Optional[int] = None):
        """One training step; returns the (device) loss as NDArray."""
        d = self._stage_input(data, self._batch_sharding(
            data.ndim if hasattr(data, "ndim") else onp.ndim(data)))
        l = self._stage_input(label, self._batch_sharding(
            label.ndim if hasattr(label, "ndim") else onp.ndim(label)))
        self._last_tokens = self._token_count(d)
        sig = (d.shape, str(d.dtype), l.shape, str(l.dtype))
        entry = self._step_cache.get(sig)
        fresh = entry is None
        if fresh:
            entry = self._build_step(*sig)
            self._step_cache[sig] = entry
        jitted, cell = entry
        from .. import profiler
        # step funnel #2: the SPMD compiled-step path
        tok = telemetry.begin_step()
        _prof_t0 = profiler.op_timer()
        try:
            with tracing.span("step.spmd") as _sp:
                self.num_update += 1
                _sp.annotate(step=self.num_update)
                lr = jnp.float32(self.optimizer.learning_rate)
                wd = jnp.float32(self.optimizer.wd)
                self.optimizer.num_update = self.num_update
                p_arrays, opt_state = self._gather_state()
                args = (next_key(), lr, wd, p_arrays, opt_state, d, l)
                if self._amp_scaler is not None:
                    args = args + (self._amp_state_in(),)
                tc = time.perf_counter() if fresh else None
                if fresh:
                    jitted, compiled_now = self._resolve_exec(
                        sig, jitted, cell, args)
                    if not compiled_now:    # artifact hit: no compile
                        tc, fresh = None, False
                with tracing.span("compile.spmd_step" if fresh
                                  else "step.dispatch"):
                    if self._amp_scaler is not None:
                        new_p, new_s, loss, aux, amp_out = jitted(*args)
                        self._amp_scaler.adopt_traced(*amp_out)
                    else:
                        new_p, new_s, loss, aux = jitted(*args)
                    telemetry.record_dispatch()
                if tc is not None:
                    telemetry.record_compile(time.perf_counter() - tc,
                                             "spmd_step")
                _sp.annotate(fresh_compile=fresh)
                self._fold_back(new_p, new_s, cell, aux)
                self._account_step_telemetry()
            profiler.op_record("SPMDTrainer::step", _prof_t0)
        finally:
            telemetry.end_step(tok, "SPMDTrainer")
        return NDArray(loss)

    def _amp_state_in(self):
        """(scale, clean-step count) as device scalars.  Reading
        ``loss_scale`` folds the PREVIOUS step's traced triple — those
        arrays are long computed, so this never blocks on in-flight
        work."""
        s = self._amp_scaler
        return (jnp.float32(s.loss_scale), jnp.float32(s._unskipped))

    def opt_state_bytes_per_device(self) -> int:
        """Optimizer-state bytes resident on the busiest device —
        ~1/dp of the replicated total under zero_stage>=1 (plus
        non-dp-divisible stragglers that stay replicated)."""
        from ..optimizer.fused_step import opt_state_bytes_per_device
        return opt_state_bytes_per_device(
            a for k in self._pkeys for a in self._opt_state[k])

    @staticmethod
    def _spec_has_dp(spec) -> bool:
        for s in spec or ():
            if s == "dp" or (isinstance(s, (tuple, list)) and "dp" in s):
                return True
        return False

    @staticmethod
    def _spec_axis_names(spec) -> set:
        used = set()
        for s in spec or ():
            if isinstance(s, (tuple, list)):
                used.update(s)
            elif s is not None:
                used.add(s)
        return used

    @staticmethod
    def _token_count(d) -> int:
        """Token count of one batch for the tp activation-volume model:
        integer inputs of rank >= 2 are (B, T) id grids — B·T tokens;
        anything else contributes its batch rows."""
        shape = getattr(d, "shape", None)
        if not shape:
            return 1
        try:
            is_int = jnp.issubdtype(d.dtype, jnp.integer)
        except Exception:
            is_int = False
        if is_int and len(shape) >= 2:
            return int(shape[0]) * int(shape[1])
        return int(shape[0])

    def _account_step_telemetry(self, n_steps: int = 1) -> None:
        """Per-step collective-byte split + opt-state residency gauge.
        GSPMD inserts the collectives inside the compiled program, where
        no host-side hook can count them, so the funnel records the
        ring-cost model instead: a replicated-update param's gradient
        allreduce moves 2(n-1)/n·bytes; a dp-sharded update moves
        reduce-scatter + all-gather (n-1)/n·bytes each — equal wire
        volume, the arxiv 2004.13336 identity the ZeRO tradeoff rests
        on.  The model is computed once (shapes and shardings are
        static per trainer)."""
        tokens = getattr(self, "_last_tokens", 1)
        model = self._comm_model
        if model is not None and model[4] != tokens:
            model = None        # batch geometry changed: re-derive
        if model is None:
            ndp = int(self.mesh.shape.get("dp", 1)) \
                if "dp" in self.mesh.axis_names else 1
            ntp = int(self.mesh.shape.get("tp", 1)) \
                if "tp" in self.mesh.axis_names else 1
            # gradient legs (reduce-scatter / allreduce) ship in the AMP
            # storage dtype under the policy; the all-gather leg returns
            # f32 master weights and stays full-width
            from ..amp import policy as _amp_policy
            gfrac = 1.0
            if self._amp_scaler is not None:
                gfrac = min(_amp_policy.compute_itemsize(), 4) / 4.0
            isz = (_amp_policy.compute_itemsize()
                   if self._amp_scaler is not None else 4)
            rs = ag = ar = tpb = 0
            for k in self._pkeys:
                p = self._params[k]
                nbytes = int(p.data()._data.nbytes)
                if ndp > 1:
                    if self._spec_has_dp(self._opt_state_sharding(p).spec):
                        rs += int(nbytes * gfrac) * (ndp - 1) // ndp
                        ag += nbytes * (ndp - 1) // ndp
                    else:
                        ar += 2 * int(nbytes * gfrac) * (ndp - 1) // ndp
                # tp activation partial-sum allreduce, one per sharded
                # matmul per direction: a column-parallel (out,in)
                # weight pays it on the backward dx (tokens × in), a
                # row-parallel one on the forward y (tokens × out) —
                # the dim the shard does NOT split
                spec = p._sharding
                shape = p.shape or ()
                if (ntp > 1 and len(shape) >= 2
                        and "tp" in self._spec_axis_names(spec)):
                    first = spec[0] if len(spec) else None
                    col = first == "tp" or (
                        isinstance(first, (tuple, list)) and "tp" in first)
                    dim = int(shape[1]) if col else int(shape[0])
                    tpb += 2 * tokens * dim * isz * (ntp - 1) // ntp
            model = self._comm_model = (rs, ag, ar, tpb, tokens)
        rs, ag, ar, tpb, _ = model
        if rs or ag:
            telemetry.record_comm_bytes(rs * n_steps, "reduce_scatter")
            telemetry.record_comm_bytes(ag * n_steps, "all_gather")
        if ar:
            telemetry.record_comm_bytes(ar * n_steps, "allreduce")
        if rs or ag or ar:
            telemetry.record_axis_comm_bytes((rs + ag + ar) * n_steps,
                                             "dp")
        if tpb:
            telemetry.record_comm_bytes(tpb * n_steps, "allreduce")
            telemetry.record_axis_comm_bytes(tpb * n_steps, "tp")
        telemetry.record_opt_state_bytes(self.opt_state_bytes_per_device())

    def _gather_state(self):
        """Current param/opt-state arrays, resharded onto the step's
        declared shardings where needed (first call after eager init
        or load: everything is committed to one device)."""
        p_arrays, opt_state = [], []
        for k in self._pkeys:
            p = self._params[k]
            p_arrays.append(self._put(p.data()._data,
                                      self._param_sharding(p)))
            shd = self._opt_state_sharding(p)
            opt_state.append(tuple(self._put(a, shd)
                                   for a in self._opt_state[k]))
        return p_arrays, opt_state

    def _fold_back(self, new_p, new_s, cell, aux=None):
        covered = set()
        for k, w, st in zip(self._pkeys, new_p, new_s):
            with ag.pause():
                self._params[k].data()._rebind(w)
            self._opt_state[k] = tuple(st)
            covered.add(id(self._params[k]))
        # aux params outside collect_params (none in practice) still get
        # their traced update; covered ones already flowed through new_p
        # in the step's own dtype discipline
        if aux is not None:
            for (param, _), new in zip(cell["aux"], aux):
                if id(param) not in covered:
                    param._data._rebind(new)

    def run_steps(self, data, label, n_steps: int,
                  per_step_data: bool = False):
        """Run ``n_steps`` fused training steps in ONE device program
        (lax.scan); returns the per-step losses as an (n_steps,)
        NDArray.

        This is the device-side training loop: one launch per window, so
        per-step dispatch/launch latency is amortized away — the XLA
        analogue of the reference executing a whole bulked segment as a
        single engine op (cached_op.cc:499-513).  lr/wd are frozen for
        the window; ``num_update`` advances by ``n_steps``.

        With ``per_step_data=True``, ``data``/``label`` carry a leading
        ``n_steps`` axis and the scan consumes one REAL batch per step —
        the feed-the-chip window: stage a whole window of input-pipeline
        batches onto the device in one transfer, then train through them
        in one launch."""
        shard_of = (self._window_sharding if per_step_data
                    else self._batch_sharding)
        d = self._stage_input(data, shard_of(
            data.ndim if hasattr(data, "ndim") else onp.ndim(data)))
        l = self._stage_input(label, shard_of(
            label.ndim if hasattr(label, "ndim") else onp.ndim(label)))
        if per_step_data and (d.shape[0] != n_steps
                              or l.shape[0] != n_steps):
            raise MXNetError(
                f"run_steps(per_step_data=True): leading axis must be "
                f"n_steps={n_steps}, got data {d.shape} label {l.shape}")
        self._last_tokens = self._token_count(
            d[0] if per_step_data else d)
        sig = (d.shape, str(d.dtype), l.shape, str(l.dtype), int(n_steps),
               bool(per_step_data))
        entry = self._step_cache.get(sig)
        fresh = entry is None
        if fresh:
            entry = self._build_multi(d.shape, str(d.dtype), l.shape,
                                      str(l.dtype), int(n_steps),
                                      per_step_data=per_step_data)
            self._step_cache[sig] = entry
        jitted, cell = entry
        # one telemetry record for the whole fused window (it IS one
        # device program / one dispatch)
        tok = telemetry.begin_step()
        try:
            with tracing.span("step.spmd_window", n_steps=int(n_steps),
                              step=self.num_update + 1):
                # read lr/wd BEFORE advancing num_update — matching what
                # the first of n sequential step() calls would use (the
                # whole fused window trains at the window-entry schedule
                # point)
                lr = jnp.float32(self.optimizer.learning_rate)
                wd = jnp.float32(self.optimizer.wd)
                self.num_update += int(n_steps)
                self.optimizer.num_update = self.num_update
                p_arrays, opt_state = self._gather_state()
                args = (next_key(), lr, wd, p_arrays, opt_state, d, l)
                if self._amp_scaler is not None:
                    args = args + (self._amp_state_in(),)
                tc = time.perf_counter() if fresh else None
                if fresh:
                    jitted, compiled_now = self._resolve_exec(
                        sig, jitted, cell, args)
                    if not compiled_now:    # artifact hit: no compile
                        tc, fresh = None, False
                with tracing.span("compile.spmd_step" if fresh
                                  else "step.dispatch"):
                    if self._amp_scaler is not None:
                        new_p, new_s, losses, amp_out = jitted(*args)
                        self._amp_scaler.adopt_traced(*amp_out)
                    else:
                        new_p, new_s, losses = jitted(*args)
                    # the whole fused window is ONE executable launch —
                    # the record's ``dispatches`` delta asserts it
                    telemetry.record_dispatch()
                if tc is not None:
                    telemetry.record_compile(time.perf_counter() - tc,
                                             "spmd_step")
                self._fold_back(new_p, new_s, cell)
                self._account_step_telemetry(n_steps=int(n_steps))
        finally:
            telemetry.end_step(tok, "SPMDTrainer",
                               extra={"n_steps": int(n_steps)})
        return NDArray(losses)

    def predict(self, data):
        """Jitted inference forward on the training mesh (params stay
        sharded; the batch is dp-sharded like in ``step``).  Fills the
        gap users hit right after SPMD training: an eager ``net(x)``
        would collide single-device inputs with mesh-committed params."""
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        sig = ("predict", d.shape, str(d.dtype))
        entry = self._step_cache.get(sig)
        if entry is None:
            net = self.net
            params = [self._params[k] for k in self._pkeys]
            amp = self.amp_dtype
            key0 = next_key()   # fetched outside the trace; eval mode
                                # draws no entropy in practice

            def fwd(p_arrays, x):
                from ..gluon.block import _TraceContext, _trace_scope
                tc = _TraceContext(key0)
                saved = [p._data for p in params]
                if self._data_transform is not None:
                    # same device-side preprocessing as the train step
                    # (a uint8-wire trainer must not see raw pixels at
                    # inference either)
                    x = self._data_transform(x)
                if amp is not None:
                    p_arrays = [a.astype(amp) if jnp.issubdtype(
                        a.dtype, jnp.floating) else a for a in p_arrays]
                    x = x.astype(amp) if jnp.issubdtype(
                        x.dtype, jnp.floating) else x
                try:
                    for p, a in zip(params, p_arrays):
                        p._data = NDArray(a)
                    with _trace_scope(tc), ag.pause(train_mode=False):
                        out = net.forward(NDArray(x))
                    return out._data.astype(jnp.float32)
                finally:
                    for p, s in zip(params, saved):
                        p._data = s

            p_shardings, _ = self._state_shardings(params)
            jitted = jax.jit(fwd, in_shardings=(
                p_shardings, self._batch_sharding(d.ndim)))
            entry = (jitted, None)
            self._step_cache[sig] = entry
        jitted, _ = entry
        d = self._put(d, self._batch_sharding(d.ndim))
        p_arrays = [self._put(self._params[k].data()._data,
                              self._param_sharding(self._params[k]))
                    for k in self._pkeys]
        return NDArray(jitted(p_arrays, d))

    def cost_analysis(self, data, label, n_steps=None):
        """XLA cost analysis (flops/bytes) for the compiled step that
        matches ``(data, label)``'s signature.  Used by bench.py for MFU
        accounting; the step must have been run at least once.

        Note: the AOT ``lower().compile()`` path does not share the jit
        call cache, so this costs one extra compile per signature (a
        disk hit when ``jax_compilation_cache_dir`` is set, as bench.py
        does); the result is memoized."""
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        l = label._data if isinstance(label, NDArray) else jnp.asarray(label)
        sig = (d.shape, str(d.dtype), l.shape, str(l.dtype))
        if n_steps is not None:
            sig = sig + (int(n_steps), False)
        cached = getattr(self, "_cost_cache", {}).get(sig)
        if cached is not None:
            return cached
        jitted, _ = self._step_cache[sig]
        p_arrays = [self._params[k].data()._data for k in self._pkeys]
        opt_state = [self._opt_state[k] for k in self._pkeys]
        lr = jnp.float32(self.optimizer.learning_rate)
        wd = jnp.float32(self.optimizer.wd)
        if self._amp_scaler is not None:
            compiled = jitted.lower(next_key(), lr, wd, p_arrays,
                                    opt_state, d, l,
                                    self._amp_state_in()).compile()
        else:
            compiled = jitted.lower(next_key(), lr, wd, p_arrays,
                                    opt_state, d, l).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out = dict(ca or {})
        try:
            ma = compiled.memory_analysis()
            out["temp_size_in_bytes"] = int(ma.temp_size_in_bytes)
            out["argument_size_in_bytes"] = int(ma.argument_size_in_bytes)
            out["output_size_in_bytes"] = int(ma.output_size_in_bytes)
        except Exception:
            pass        # some backends expose cost but not memory stats
        if not hasattr(self, "_cost_cache"):
            self._cost_cache = {}
        self._cost_cache[sig] = out
        return out

    def save_states(self, fname):
        """Checkpoint optimizer state + step counter + the global PRNG
        key chain (parity: Trainer.save_states / kvstore get_states).
        Sharded state is gathered to host — on a multi-host mesh call
        on every process; rank 0's file is authoritative (identical
        contents by construction).

        Format: numpy .npz with a JSON header under ``__header__`` and
        one entry per state slot named ``<param>::<slot>`` — no pickle,
        so untrusted checkpoints cannot execute code on load."""
        import json
        from ..ops import random as _rand
        arrays = {}
        slots = {}
        dtypes = {}
        for k, st in self._opt_state.items():
            slots[k] = len(st)
            dtypes[k] = []
            for i, s in enumerate(st):
                d = onp.asarray(jax.device_get(s))
                dtypes[k].append(str(d.dtype))
                if d.dtype.kind not in "biufc":
                    # ml_dtypes (bfloat16, fp8) save as raw void in npz;
                    # store the bit pattern as uint of the same width
                    d = d.view(onp.dtype(f"u{d.dtype.itemsize}"))
                arrays[f"{k}::{i}"] = d
        header = json.dumps({"format": "mxnet_tpu-trainer-states-v1",
                             "num_update": self.num_update,
                             "rng_key": [int(w) for w in
                                         _rand.get_state_bits().ravel()],
                             "slots": slots, "dtypes": dtypes})
        arrays["__header__"] = onp.frombuffer(
            header.encode("utf-8"), dtype=onp.uint8)
        with open(fname, "wb") as f:
            onp.savez(f, **arrays)

    def load_states(self, fname):
        """Restore optimizer state (and, when present, the global PRNG
        chain) saved by :meth:`save_states`; arrays are re-placed under
        each parameter's declared sharding.  Only the .npz format
        written by :meth:`save_states` is accepted
        (``allow_pickle=False`` — loading never executes code)."""
        import json
        from ..ops import random as _rand
        with onp.load(fname, allow_pickle=False) as z:
            if "__header__" not in z:
                raise MXNetError(
                    f"{fname}: not a mxnet_tpu trainer-states file")
            header = json.loads(bytes(z["__header__"]).decode("utf-8"))
            if header.get("format") != "mxnet_tpu-trainer-states-v1":
                raise MXNetError(
                    f"{fname}: unknown trainer-states format "
                    f"{header.get('format')!r}")
            self.num_update = int(header["num_update"])
            self.optimizer.num_update = self.num_update
            if header.get("rng_key"):
                _rand.set_state_bits(header["rng_key"])
            dtypes = header.get("dtypes", {})

            def _restore(k, i):
                raw = z[f"{k}::{i}"]
                # per-key lookup with default (no magic-length list:
                # an optimizer with any number of state slots works)
                key_dtypes = dtypes.get(k) or []
                want = key_dtypes[i] if i < len(key_dtypes) else None
                if want is not None and str(raw.dtype) != want:
                    import ml_dtypes  # noqa: F401 (registers dtype names)
                    raw = raw.view(onp.dtype(want))
                return raw

            for k, n in header["slots"].items():
                if k not in self._opt_state:
                    raise MXNetError(f"unknown optimizer-state key {k!r}")
                shd = self._opt_state_sharding(self._params[k])
                self._opt_state[k] = tuple(
                    jax.device_put(jnp.asarray(_restore(k, i)), shd)
                    for i in range(int(n)))

    # -- checkpoint/resume (the recovery story, SURVEY §5: no elastic
    #    restart in the reference either — checkpoint/resume IS the
    #    failure-handling design; here it is turnkey and ASYNC) --------
    def save_checkpoint(self, directory, tag="latest", meta=None,
                        block=True):
        """Checkpoint params + optimizer state + step counter + global
        PRNG chain through the async sharded checkpoint service
        (``mxnet_tpu.checkpoint``): the step path pays only a
        non-blocking per-shard D2H snapshot; per-device shard files and
        the crash-durable manifest/rename publish happen on the writer
        thread.  ``meta``: extra JSON (e.g. fit progress / data cursor)
        stored in the manifest header.

        ``block=True`` (default) waits for the publish and returns the
        final checkpoint path, raising ``MXNetError`` if the save
        failed after retries.  ``block=False`` returns a
        ``checkpoint.PendingSave`` immediately — a failed async save
        logs + increments ``checkpoint.failures`` telemetry, never
        raises into the training step.

        Multi-process runs route through the rank-0 commit protocol:
        every rank calls this with its OWN addressable shards (the
        snapshot only captures what this process holds), writes a
        ready marker, and only rank 0 publishes the merged manifest —
        rank/world come from ``checkpoint.rank_world()`` (env >
        kvstore plumbing > ``jax.process_index()``)."""
        from .. import checkpoint as _ckpt
        from ..ops import random as _rand

        tree = {}
        for k in self._pkeys:
            tree[f"param/{k}"] = self._params[k].data()._data
        for k in self._pkeys:
            for i, s in enumerate(self._opt_state[k]):
                tree[f"opt/{k}/{i}"] = s
        header = {
            "num_update": int(self.num_update),
            "rng_key": [int(w) for w in _rand.get_state_bits().ravel()],
            "slots": {k: len(self._opt_state[k]) for k in self._pkeys},
            "meta": dict(meta or {}),
            # mesh provenance (informational — restore re-places global
            # arrays under the LOADING trainer's mesh, so a dp2×tp2
            # save restores onto dp4×tp1; the header just records where
            # the bytes came from for post-mortems)
            "mesh_axes": {ax: int(self.mesh.shape[ax])
                          for ax in self.mesh.axis_names},
        }
        # AMP provenance: the tree always holds fp32 MASTER weights (the
        # compute-dtype casts live in the traced step, never in the
        # stored arrays), so a checkpoint written under AMP loads into an
        # AMP-off run — and across compute dtypes — unchanged.  The
        # header records the policy + scaler state for deterministic
        # loss-scale resume.
        if self._amp_scaler is not None:
            from ..amp import policy as _amp_policy
            header["amp"] = {
                "enabled": True,
                "compute_dtype": _amp_policy.compute_dtype_str(),
                "scaler": self._amp_scaler.state(),
            }
        rank, world = _ckpt.rank_world()
        job = _ckpt.save(directory, tree, header, tag=tag, block=block,
                         rank=rank, world=world)
        return job.result() if block else job

    def load_checkpoint(self, directory, tag="latest"):
        """Restore a :meth:`save_checkpoint` snapshot (falling back to
        the ``tag.old`` backup if a crash interrupted a publish, then
        to the newest ``step-<n>`` directory the keep-last-N GC
        retains when both are missing or digest-corrupt).
        Shards are reassembled to GLOBAL arrays and re-placed under
        THIS trainer's mesh/shardings — a dp=8 save restores onto a
        dp=1 trainer bit-identically (resharded restore).  Also
        restores the step counter and the global PRNG chain, so a
        resumed run continues the exact key sequence.  Returns the
        checkpoint's meta dict (always truthy — contains at least
        ``num_update``) or None when nothing was found."""
        from .. import checkpoint as _ckpt
        from ..ops import random as _rand

        loaded = _ckpt.load(directory, tag)
        if loaded is None:
            return self._load_checkpoint_v1(directory, tag)
        leaves, header = loaded
        for k in self._pkeys:
            name = f"param/{k}"
            if name not in leaves:
                raise MXNetError(
                    f"checkpoint {directory!r} has no entry for "
                    f"parameter {k!r}")
            p = self._params[k]
            if tuple(leaves[name].shape) != tuple(p.shape):
                raise MXNetError(
                    f"checkpoint parameter {k!r} has shape "
                    f"{tuple(leaves[name].shape)}, model expects "
                    f"{tuple(p.shape)}")
            arr = jax.device_put(jnp.asarray(leaves[name]),
                                 self._param_sharding(p))
            with ag.pause():
                p.data()._rebind(arr)
        slots = header.get("slots") or {}
        for k in self._pkeys:
            n = int(slots.get(k, len(self._opt_state[k])))
            shd = self._opt_state_sharding(self._params[k])
            st = []
            for i in range(n):
                name = f"opt/{k}/{i}"
                if name not in leaves:
                    raise MXNetError(
                        f"checkpoint {directory!r} has no entry for "
                        f"optimizer state {name!r}")
                st.append(jax.device_put(jnp.asarray(leaves[name]), shd))
            self._opt_state[k] = tuple(st)
        self.num_update = int(header.get("num_update", self.num_update))
        self.optimizer.num_update = self.num_update
        if header.get("rng_key"):
            _rand.set_state_bits(header["rng_key"])
        # deterministic loss-scale resume; an AMP-on checkpoint into an
        # AMP-off trainer (or vice versa) just drops/starts the scaler
        # schedule — the weights themselves are dtype-portable masters
        amp_hdr = header.get("amp")
        if amp_hdr and self._amp_scaler is not None \
                and amp_hdr.get("scaler"):
            self._amp_scaler.load_state(amp_hdr["scaler"])
        meta = dict(header.get("meta") or {})
        meta["num_update"] = self.num_update
        return meta

    def _load_checkpoint_v1(self, directory, tag):
        """Legacy (pre-manifest) checkpoint layout: a directory with
        ``model.params`` + ``trainer.npz`` (+ optional ``meta.json``)."""
        import json
        import os

        for cand in (os.path.join(directory, tag),
                     os.path.join(directory, f"{tag}.old")):
            if os.path.isfile(os.path.join(cand, "model.params")):
                break
        else:
            return None
        meta = {}
        meta_path = os.path.join(cand, "meta.json")
        if os.path.exists(meta_path):   # optional (hand-copied ckpts)
            with open(meta_path) as f:
                meta = dict(json.load(f))
        self.net.load_parameters(os.path.join(cand, "model.params"))
        self.load_states(os.path.join(cand, "trainer.npz"))
        meta["num_update"] = self.num_update
        return meta

    def fit(self, data_iter, epochs=1, verbose=False,
            checkpoint_dir=None, checkpoint_every=0, resume=True):
        """Epoch loop over ``data_iter``.  With ``checkpoint_dir``,
        checkpoints every ``checkpoint_every`` steps (async — the step
        path pays only the device snapshot) and at the end (blocking,
        so a returned fit implies a published checkpoint), and
        auto-resumes from the latest checkpoint on start — kill the
        process anywhere and re-running ``fit`` continues from the
        last published checkpoint.

        Resume is deterministic: the checkpoint carries the global
        PRNG key chain (restored on load — the resumed run draws the
        exact dropout/shuffle keys the uninterrupted run would have)
        and the data cursor (epoch + batch index; already-consumed
        batches replay without training, via
        ``DevicePrefetcher.fast_forward`` when the iterator supports
        it so the replay skips the H2D transfers too)."""
        skip = 0
        if checkpoint_dir and resume:
            meta = self.load_checkpoint(checkpoint_dir)
            if meta:
                # skip exactly the batches THIS fit already consumed
                # (recorded in the checkpoint's meta — the global
                # num_update may include steps taken outside fit)
                skip = int(meta.get("fit_seen", 0))
        losses = []
        seen = 0
        fast_forward = getattr(data_iter, "fast_forward", None)
        for epoch in range(epochs):
            batch_idx = 0
            if seen < skip and fast_forward is not None:
                # skip whole prefixes device-free when the source knows
                # its epoch length (DevicePrefetcher over a sized
                # loader); otherwise fall through to consume-and-drop
                try:
                    epoch_len = len(data_iter)
                except TypeError:
                    epoch_len = None
                if epoch_len is not None:
                    n = min(skip - seen, epoch_len)
                    fast_forward(n)
                    seen += n
                    batch_idx = n
            for batch in data_iter:
                seen += 1
                if seen <= skip:
                    continue        # replayed data before resume point
                batch_idx += 1
                d, l = batch[0], batch[1]
                losses.append(self.step(d, l))
                if (checkpoint_dir and checkpoint_every
                        and len(losses) % checkpoint_every == 0):
                    self.save_checkpoint(
                        checkpoint_dir, block=False,
                        meta={"fit_seen": seen,
                              "cursor": {"epoch": epoch,
                                         "batch": batch_idx}})
        if checkpoint_dir:
            # blocking final save: the writer queue is FIFO, so this
            # also drains every earlier async save before returning
            self.save_checkpoint(
                checkpoint_dir,
                meta={"fit_seen": seen,
                      "cursor": {"epoch": epochs - 1, "batch": seen}})
        return losses
