"""Mixture-of-experts with expert parallelism over a mesh axis.

TPU-native capability (no reference counterpart — the reference has no
MoE): Switch-style top-1 routing in the Mesh-TensorFlow einsum
formulation.  Expert weights carry a leading E axis sharded over the
``ep`` mesh axis; the dispatch/combine einsums contract token×expert
one-hots against expert-major activations, so under GSPMD the
token→expert shuffle lowers to all_to_all over ICI — no hand-written
collectives.

Shapes: tokens (N, H); gate (H, E); experts w1 (E, H, F), b1 (E, F),
w2 (E, F, H), b2 (E, H).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import telemetry

__all__ = ["switch_moe", "moe_expert_sharding"]


def switch_moe(x, gate_w, w1, b1, w2, b2, capacity_factor: float = 1.25,
               return_stats: bool = False):
    """Top-1 (Switch) MoE layer.

    Tokens route to their argmax expert, subject to a per-expert
    capacity of ``ceil(N/E * capacity_factor)`` — overflow tokens pass
    through with zero expert output (standard Switch behavior, which
    keeps every shape static for XLA).  Dropped tokens are ACCOUNTED,
    never silent: an eager call ticks the ``moe.dropped_tokens``
    telemetry counter directly; a traced caller passes
    ``return_stats=True`` and folds ``stats['dropped_tokens']`` out of
    the executable (Mesh4DTrainer records it per window via
    ``telemetry.record_moe_dropped``).

    Returns ``(y, aux_loss)`` where ``aux_loss`` is the Switch
    load-balancing loss (E · Σ_e f_e · p̄_e) to be added to the training
    objective — or ``(y, aux_loss, stats)`` with ``return_stats=True``,
    where ``stats`` carries ``dropped_tokens`` (int32 scalar),
    ``capacity`` (static int) and ``expert_load`` ((E,) tokens routed
    per expert, pre-drop).
    """
    n, h = x.shape
    e = gate_w.shape[1]
    cap = max(1, math.ceil(n / e * capacity_factor))

    logits = x @ gate_w                                   # (N, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                   # (N,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    onehot = jax.nn.one_hot(expert, e, dtype=x.dtype)     # (N, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) - onehot             # (N, E)
    keep = (pos < cap).astype(x.dtype) * onehot
    slot = jnp.einsum("ne,nec->nec", keep,
                      jax.nn.one_hot(pos.astype(jnp.int32), cap,
                                     dtype=x.dtype))      # (N,E,C)

    # dispatch: tokens → expert-major buffers (all_to_all under GSPMD)
    xe = jnp.einsum("nec,nh->ech", slot, x)               # (E, C, H)
    hdn = jax.nn.relu(jnp.einsum("ech,ehf->ecf", xe, w1)
                      + b1[:, None, :])                   # (E, C, F)
    ye = jnp.einsum("ecf,efh->ech", hdn, w2) + b2[:, None, :]

    # combine: expert outputs → token order, weighted by the gate
    combine = slot * gate[:, None, None]
    y = jnp.einsum("nec,ech->nh", combine, ye)            # (N, H)

    # load-balancing loss (Switch Transformer eq. 4)
    frac_tokens = jnp.mean(onehot, axis=0)                # f_e
    frac_probs = jnp.mean(probs, axis=0)                  # p̄_e
    aux = e * jnp.sum(frac_tokens * frac_probs)

    # capacity-overflow accounting: tokens the cap zeroed out.  keep is
    # exactly onehot minus the overflow rows, so N - Σkeep IS the drop.
    dropped = (n - jnp.sum(keep)).astype(jnp.int32)
    if return_stats:
        stats = {"dropped_tokens": dropped, "capacity": cap,
                 "expert_load": jnp.sum(onehot, axis=0)}
        return y, aux, stats
    if not isinstance(dropped, jax.core.Tracer):
        # eager call: the count is concrete — account it here
        telemetry.record_moe_dropped(int(dropped))
    return y, aux


def moe_expert_sharding(mesh: Mesh, axis_name: str = "ep"):
    """NamedShardings for (gate_w, w1, b1, w2, b2): gate replicated,
    expert weights sharded on the leading E axis over ``axis_name``."""
    rep = NamedSharding(mesh, PartitionSpec())
    ex = NamedSharding(mesh, PartitionSpec(axis_name))
    return rep, ex, ex, ex, ex
