"""Composable 4-D parallelism: dp × tp × pp × ep on ONE mesh.

The scale lever ROADMAP names: a single :class:`MeshPlan` builds one
``jax.sharding.Mesh`` carrying every parallelism axis the framework
knows (dp/tp/pp/sp/ep, the mesh.py convention) and derives composed
per-leaf ``NamedSharding``s from it, so the SAME compiled program
combines:

- **dp** — ZeRO weight-update sharding (arxiv 2004.13336): gradients
  reduce-scatter onto the dp shards that own the optimizer state, the
  updated weights all-gather back.  :meth:`MeshPlan.zero_spec` composes
  the dp shard onto whatever other axes a leaf already carries.
- **tp** — GSPMD tensor parallelism: column→row matmul pairs
  constrained with ``with_sharding_constraint``
  (:meth:`MeshPlan.tp_column` / :meth:`MeshPlan.tp_row`); XLA inserts
  the activation partial-sum allreduce over 'tp'.
- **pp** — the existing :func:`..pipeline.one_f_one_b_apply` 1F1B
  lax-loop schedule, lifted by :class:`Mesh4DTrainer` so a whole
  ``run_steps`` window stays ONE dispatch (PAPERS.md 1810.09868).
- **ep** — :func:`..moe.switch_moe` expert dispatch: expert weights
  sharded over 'ep', the dispatch/combine einsums lower to all_to_all.

Axis sizes come from the constructor or ``MXNET_MESH`` (e.g.
``MXNET_MESH=dp2,tp2`` — docs/ENV_VARS.md).  Requested axes are KEPT
even at size 1, so a ``PartitionSpec`` mentioning 'tp' stays valid on a
dp4×tp1 mesh — which is what lets an AMP/ZeRO checkpoint saved under
dp2×tp2 restore onto dp4×tp1: the checkpoint service reassembles
global arrays and this plan just re-places them.

Every collective each axis carries is attributed to it through
``telemetry.record_axis_comm_bytes`` (``comm.dp.bytes``,
``comm.tp.bytes``, …) via the same analytic ring-cost model the dp-only
funnels use — GSPMD inserts the collectives inside the executable where
no host hook can count them, so the model is the accounting.
"""
from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as onp
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import telemetry
from ..base import MXNetError
from .mesh import make_mesh
from .pipeline import pipeline_value_and_grad_1f1b

__all__ = ["MeshPlan", "Mesh4DTrainer", "mesh_plan_from_env"]

# device-grid axis order: pp outermost (stages are the coarsest, often
# cross-slice boundary), tp innermost (its activation allreduces are
# the latency-critical ones and want the tightest ICI ring)
_AXIS_ORDER = ("pp", "dp", "ep", "sp", "tp")


class MeshPlan:
    """One mesh, every parallelism axis, composed shardings.

    ``MeshPlan(dp=2, tp=2)`` on 4+ devices builds a mesh whose axis
    names are exactly the requested ones (size-1 axes INCLUDED — specs
    naming them stay valid, the cross-mesh-restore requirement).
    ``dp=-1`` fills the devices the named axes leave over.
    """

    def __init__(self, dp: int = -1, tp: int = 1, pp: int = 1,
                 ep: int = 1, sp: int = 1,
                 devices: Optional[Sequence[jax.Device]] = None):
        sizes = {"dp": int(dp), "tp": int(tp), "pp": int(pp),
                 "ep": int(ep), "sp": int(sp)}
        for ax, s in sizes.items():
            if s == 0 or s < -1:
                raise MXNetError(f"MeshPlan: bad {ax}={s} (>=1, or "
                                 f"dp=-1 to fill)")
            if s == -1 and ax != "dp":
                raise MXNetError(f"MeshPlan: only dp may be -1, got "
                                 f"{ax}=-1")
        self._mesh = make_mesh({ax: sizes[ax] for ax in _AXIS_ORDER},
                               devices)
        self.dp = int(self._mesh.shape["dp"])
        self.tp = int(self._mesh.shape["tp"])
        self.pp = int(self._mesh.shape["pp"])
        self.ep = int(self._mesh.shape["ep"])
        self.sp = int(self._mesh.shape["sp"])

    # -- construction ------------------------------------------------------
    @classmethod
    def from_env(cls, default: Optional[str] = None,
                 devices: Optional[Sequence[jax.Device]] = None
                 ) -> Optional["MeshPlan"]:
        """Build from ``MXNET_MESH`` (``dp2,tp2`` / ``dp=2,tp=2`` /
        ``dp:2 tp:2``); None when unset and no ``default`` given."""
        spec = os.environ.get("MXNET_MESH", default)
        if not spec:
            return None
        sizes: Dict[str, int] = {}
        for tok in re.split(r"[,\s]+", spec.strip()):
            if not tok:
                continue
            m = re.fullmatch(r"(dp|tp|pp|ep|sp)[=:]?(-?\d+)", tok)
            if m is None:
                raise MXNetError(
                    f"MXNET_MESH: cannot parse {tok!r} in {spec!r} "
                    f"(expected e.g. dp2,tp2)")
            sizes[m.group(1)] = int(m.group(2))
        return cls(devices=devices, **sizes)

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return {ax: int(self._mesh.shape[ax]) for ax in _AXIS_ORDER}

    def describe(self) -> str:
        """One-line mesh summary for logs/reports."""
        live = [f"{ax}{n}" for ax, n in self.axis_sizes.items() if n > 1]
        return "×".join(live) if live else "single-device"

    # -- shardings ---------------------------------------------------------
    def named(self, *spec) -> NamedSharding:
        return NamedSharding(self._mesh, PartitionSpec(*spec))

    @property
    def replicated(self) -> NamedSharding:
        return self.named()

    def batch_spec(self, ndim: int, batch_axis: int = 0,
                   seq_axis: Optional[int] = None) -> PartitionSpec:
        """Batch tensors: dp on the batch axis, sp on the sequence axis
        when sequence parallelism is requested."""
        spec = [None] * ndim
        if batch_axis < ndim:
            spec[batch_axis] = "dp"
        if (seq_axis is not None and seq_axis < ndim
                and seq_axis != batch_axis and self.sp > 1):
            spec[seq_axis] = "sp"
        return PartitionSpec(*spec)

    def batch_sharding(self, ndim: int, batch_axis: int = 0,
                       seq_axis: Optional[int] = None) -> NamedSharding:
        return NamedSharding(self._mesh,
                             self.batch_spec(ndim, batch_axis, seq_axis))

    @staticmethod
    def column_spec(ndim: int = 2) -> PartitionSpec:
        """Column-parallel weight in the gluon (out, in) layout: the
        OUTPUT dim sharded over 'tp' (each tp shard computes a slice of
        the activations; no forward collective)."""
        return PartitionSpec(*(("tp",) + (None,) * (ndim - 1)))

    @staticmethod
    def row_spec(ndim: int = 2) -> PartitionSpec:
        """Row-parallel weight in the gluon (out, in) layout: the INPUT
        dim sharded over 'tp' (partial sums — the forward allreduce the
        column→row pair pays once)."""
        return PartitionSpec(*((None,) * (ndim - 1) + ("tp",)))

    def tp_column(self, x, feature_axis: int = -1):
        """Constrain a column-parallel matmul's output: feature axis
        sharded over 'tp'.  GSPMD then keeps the following elementwise
        ops sharded instead of gathering."""
        ax = feature_axis % x.ndim
        spec = [None] * x.ndim
        spec[ax] = "tp"
        return jax.lax.with_sharding_constraint(x, self.named(*spec))

    def tp_row(self, x):
        """Constrain a row-parallel matmul's output replicated over
        'tp' — the point GSPMD materializes the partial-sum allreduce
        (the column→row pair's single forward collective)."""
        return jax.lax.with_sharding_constraint(
            x, self.named(*([None] * x.ndim)))

    @staticmethod
    def _spec_axes(spec) -> set:
        used = set()
        for s in spec or ():
            if isinstance(s, (tuple, list)):
                used.update(s)
            elif s is not None:
                used.add(s)
        return used

    def zero_spec(self, shape, base_spec: Optional[PartitionSpec] = None
                  ) -> Optional[PartitionSpec]:
        """Compose the ZeRO dp-shard onto ``base_spec``: the largest
        still-unsharded dp-divisible axis takes 'dp'.  Returns the
        composed spec, or ``base_spec`` unchanged (possibly None) when
        nothing divides — small biases stay replicated, their memory is
        noise.  This is the per-leaf composition rule the tentpole is
        about: a P(None, 'tp') row weight's optimizer state becomes
        P('dp', 'tp') — sharded over BOTH axes, 1/(dp·tp) per device.
        """
        if self.dp <= 1:
            return base_spec
        base = list(base_spec) if base_spec is not None else []
        base += [None] * (len(shape or ()) - len(base))
        used = self._spec_axes(base)
        if "dp" in used:
            return base_spec
        best = None
        for ax, dim in enumerate(shape or ()):
            if base[ax] is not None:
                continue            # already carries tp/pp/ep/sp
            if dim % self.dp == 0 and (best is None
                                       or dim > shape[best]):
                best = ax
        if best is None:
            return base_spec
        base[best] = "dp"
        return PartitionSpec(*base)

    def param_sharding(self, spec: Optional[PartitionSpec]
                       ) -> NamedSharding:
        return NamedSharding(self._mesh, spec or PartitionSpec())

    def opt_state_sharding(self, shape,
                           spec: Optional[PartitionSpec] = None,
                           zero: bool = True) -> NamedSharding:
        """Optimizer-state sharding for a leaf of ``shape`` whose param
        carries ``spec``: the param's own axes plus (``zero=True``) the
        composed ZeRO dp-shard."""
        s = self.zero_spec(shape, spec) if zero else spec
        return NamedSharding(self._mesh, s or PartitionSpec())

    # -- analytic per-axis comm model --------------------------------------
    def ring_bytes(self, nbytes: int, axis: str,
                   kind: str = "allreduce") -> int:
        """Ring-cost wire bytes for one collective of ``nbytes`` payload
        over ``axis``: allreduce 2(n-1)/n, reduce_scatter / all_gather /
        all_to_all (n-1)/n, ppermute the full payload per hop."""
        n = self.axis_sizes.get(axis, 1)
        if n <= 1:
            return 0
        if kind == "allreduce":
            return 2 * int(nbytes) * (n - 1) // n
        if kind == "ppermute":
            return int(nbytes)
        return int(nbytes) * (n - 1) // n


def mesh_plan_from_env() -> Optional[MeshPlan]:
    """The process-wide ``MXNET_MESH`` plan, or None when unset.  The
    SPMD funnels consult this when no mesh was passed, so exporting
    ``MXNET_MESH=dp2,tp2`` re-lays a run with no code change."""
    return MeshPlan.from_env()


class Mesh4DTrainer:
    """Functional 4-D trainer: one jitted program per ``run_steps``
    window composing dp (ZeRO), tp (GSPMD constraints or stage-level
    psum), pp (1F1B), ep (MoE all_to_all) and the AMP policy.

    Two composition paths, chosen by the plan's pp size:

    - ``pp == 1`` — **GSPMD path**: ``stage_fn(params, x)`` is a plain
      traced function; tensor parallelism comes from the param specs +
      ``plan.tp_column``/``tp_row`` constraints, expert parallelism
      from specs carrying 'ep' (switch_moe's einsums lower to
      all_to_all).  ``stage_fn`` may return ``(out, aux_loss)`` (e.g.
      the Switch load-balancing loss) or ``(out, aux_loss, dropped)``
      to surface capacity-dropped token counts into telemetry.
    - ``pp > 1`` — **1F1B path**: ``stage_fn(stage_params, h)`` is the
      per-stage function :func:`..pipeline.one_f_one_b_apply` runs
      under shard_map; param leaves carry a leading stage axis of size
      pp and specs like ``P('pp', None, 'tp')``; intra-stage tensor
      parallelism uses ``lax.psum(..., 'tp')`` (the stage_fn owns its
      collectives — examples/parallel/pipeline_1f1b_3d.py is the
      template).  Specs carrying 'ep' are rejected here: expert
      parallelism composes on the GSPMD path.

    Either way the optimizer (SGD + momentum) updates under composed
    ZeRO shardings — ``with_sharding_constraint`` on the momentum/new
    weights makes GSPMD emit reduce-scatter(grad) → sharded update →
    all-gather(weight) on the dp axis — and the AMP policy's storage
    dtype rides every gradient wire.  ``run_steps`` scans the whole
    window inside ONE executable: exactly one dispatch per window.

    Checkpoints go through the async sharded checkpoint service; the
    saved tree holds fp32 masters as GLOBAL arrays, so a dp2×tp2 save
    restores bit-identically onto a dp4×tp1 plan.
    """

    def __init__(self, plan: MeshPlan, stage_fn: Callable,
                 loss_fn: Callable, params, *,
                 param_specs=None, learning_rate: float = 0.01,
                 momentum: float = 0.9, weight_decay: float = 0.0,
                 n_microbatches: Optional[int] = None,
                 zero: bool = True, donate: bool = True):
        self.plan = plan
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.learning_rate = float(learning_rate)
        self.momentum = float(momentum)
        self.weight_decay = float(weight_decay)
        self.zero = bool(zero)
        self._donate = bool(donate)
        self.n_microbatches = int(n_microbatches
                                  if n_microbatches is not None
                                  else max(plan.pp, 1))
        self.num_update = 0
        self._cache: Dict[Any, Any] = {}
        self._comm_model: Optional[Dict[str, int]] = None

        leaves, self._treedef = jax.tree_util.tree_flatten(params)
        if param_specs is None:
            specs = [None] * len(leaves)
        else:
            specs = jax.tree_util.tree_flatten(
                param_specs, is_leaf=lambda s: s is None
                or isinstance(s, PartitionSpec))[0]
        if len(specs) != len(leaves):
            raise MXNetError(
                f"param_specs has {len(specs)} leaves, params "
                f"{len(leaves)}")
        if plan.pp > 1:
            for lf, sp in zip(leaves, specs):
                if lf.shape[0] != plan.pp:
                    raise MXNetError(
                        f"pp={plan.pp}: param leaf {lf.shape} must "
                        f"carry a leading stage axis of size pp")
                if "ep" in MeshPlan._spec_axes(sp):
                    raise MXNetError(
                        "expert parallelism ('ep' in a param spec) "
                        "composes on the GSPMD path (pp=1); in-pipeline "
                        "MoE runs with replicated experts")
        self._specs = specs
        # masters are fp32 on device under their composed shardings;
        # momentum under the ZeRO-composed shardings
        self._p_shardings = [plan.param_sharding(s) for s in specs]
        self._m_shardings = [plan.opt_state_sharding(l.shape, s,
                                                     zero=self.zero)
                             for l, s in zip(leaves, specs)]
        self._params = [jax.device_put(
            jnp.asarray(l, jnp.float32)
            if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
            else jnp.asarray(l), sh)
            for l, sh in zip(leaves, self._p_shardings)]
        self._momentum = [jax.device_put(jnp.zeros(l.shape, jnp.float32),
                                         sh)
                          for l, sh in zip(leaves, self._m_shardings)]

        from ..amp import policy as _amp_policy
        self._amp = _amp_policy.enabled()
        if self._amp:
            self._compute_dtype = jnp.dtype(_amp_policy.compute_dtype())
            init = (2.0 ** 16
                    if _amp_policy.compute_dtype_str() == "float16"
                    else 1.0)
            self._scale = jnp.float32(init)
            self._good = jnp.float32(0.0)
        else:
            self._compute_dtype = None

    # -- pytree views ------------------------------------------------------
    @property
    def params(self):
        """Current fp32 master params as the constructor's pytree."""
        return jax.tree_util.tree_unflatten(self._treedef, self._params)

    # -- the traced step ---------------------------------------------------
    def _cast(self, a):
        if self._compute_dtype is not None and jnp.issubdtype(
                a.dtype, jnp.floating):
            return a.astype(self._compute_dtype)
        return a

    def _value_and_grads(self, p_list, x, y, scale):
        """(mean_loss, grads[, dropped]) on either composition path.
        The loss is scaled INSIDE (so f16 gradients stay representable)
        and unscaled by the caller after the finite check."""
        plan = self.plan
        params = jax.tree_util.tree_unflatten(self._treedef, p_list)
        if plan.pp > 1:
            cfn = self._cast

            def stage(sp, h):
                return self.stage_fn(jax.tree_util.tree_map(cfn, sp),
                                     cfn(h))

            def lfn(out, t):
                loss = self.loss_fn(out, t).astype(jnp.float32)
                return loss * scale if scale is not None else loss

            pspec = jax.tree_util.tree_unflatten(
                self._treedef,
                [s if s is not None else PartitionSpec("pp")
                 for s in self._specs])
            loss, grads = pipeline_value_and_grad_1f1b(
                stage, lfn, params, self._cast(x), y, plan.mesh,
                self.n_microbatches, axis_name="pp",
                batch_axis_name="dp", param_specs=pspec)
            return loss, jax.tree_util.tree_leaves(grads), None

        def loss_of(p_list_in):
            p = jax.tree_util.tree_unflatten(
                self._treedef, [self._cast(a) for a in p_list_in])
            res = self.stage_fn(p, self._cast(x))
            dropped = None
            aux = None
            if isinstance(res, tuple):
                out = res[0]
                aux = res[1] if len(res) > 1 else None
                dropped = res[2] if len(res) > 2 else None
            else:
                out = res
            loss = self.loss_fn(out, y).astype(jnp.float32)
            if aux is not None:
                loss = loss + aux.astype(jnp.float32)
            if scale is not None:
                loss = loss * scale
            return loss, dropped

        (loss, dropped), grads = jax.value_and_grad(
            loss_of, has_aux=True)(list(p_list))
        return loss, grads, dropped

    def _constrain(self, a, sharding):
        return jax.lax.with_sharding_constraint(a, sharding)

    def _step(self, p_list, m_list, x, y, amp_state):
        """One full training step (fwd+bwd+update), traced.  Returns
        (new_p, new_m, loss, dropped, new_amp_state)."""
        from ..amp import policy as _amp_policy
        scale = amp_state[0] if self._amp else None
        loss, grads, dropped = self._value_and_grads(p_list, x, y, scale)
        lr = jnp.float32(self.learning_rate)
        mu = jnp.float32(self.momentum)
        wd = jnp.float32(self.weight_decay)

        def do_update(p_in, g_in, m_in):
            new_p, new_m = [], []
            for w, g, m, psh, msh in zip(p_in, g_in, m_in,
                                         self._p_shardings,
                                         self._m_shardings):
                g = g.astype(jnp.float32)
                if self._amp:
                    # wire discipline: the dp gradient leg ships the
                    # policy storage dtype; masters update from the
                    # dequantized value
                    g = _amp_policy.wire_cast(g)
                # reduce-scatter point: grads land dp-sharded where the
                # momentum lives
                g = self._constrain(g, msh)
                m2 = self._constrain(mu * m + g, msh)
                upd = m2 + wd * w.astype(jnp.float32)
                # all-gather point: the updated master returns to the
                # param's own sharding
                w2 = self._constrain(
                    (w.astype(jnp.float32) - lr * upd).astype(w.dtype),
                    psh)
                new_p.append(w2)
                new_m.append(m2)
            return new_p, new_m

        if not self._amp:
            new_p, new_m = do_update(list(p_list), grads, list(m_list))
            return new_p, new_m, loss, dropped, amp_state

        good = amp_state[1]
        inv = 1.0 / scale
        loss = loss * inv
        grads = [g * inv.astype(g.dtype)
                 if jnp.issubdtype(g.dtype, jnp.floating) else g
                 for g in grads]
        finite = jnp.bool_(True)
        for g in grads:
            if jnp.issubdtype(g.dtype, jnp.floating):
                finite = jnp.logical_and(finite, jnp.isfinite(g).all())

        def _apply(opnds):
            p_in, g_in, m_in = opnds
            return do_update(p_in, g_in, m_in)

        def _skip(opnds):
            p_in, _g, m_in = opnds
            return list(p_in), list(m_in)

        new_p, new_m = jax.lax.cond(
            finite, _apply, _skip, (list(p_list), grads, list(m_list)))
        # dynamic loss scale: grow after 2000 clean steps, halve on
        # overflow (the LossScaler schedule, traced)
        good1 = good + 1.0
        grown = jnp.where(good1 >= 2000.0, scale * 2.0, scale)
        new_scale = jnp.where(finite, grown,
                              jnp.maximum(scale * 0.5, 1.0))
        new_good = jnp.where(finite,
                             jnp.where(good1 >= 2000.0, 0.0, good1), 0.0)
        nskip = amp_state[2] + jnp.where(finite, 0.0, 1.0)
        return new_p, new_m, loss, dropped, (new_scale, new_good, nskip)

    def _build(self, data_shape, data_dtype, label_shape, label_dtype,
               n_steps, per_step_data):
        plan = self.plan

        def many(p_list, m_list, x, y, amp_state):
            def body(carry, xs):
                p, m, amp = carry
                d, l = (x, y) if xs is None else xs
                new_p, new_m, loss, dropped, amp = self._step(
                    p, m, d, l, amp)
                drop = (jnp.int32(0) if dropped is None
                        else dropped.astype(jnp.int32))
                return (new_p, new_m, amp), (loss, drop)
            (p, m, amp), (losses, drops) = jax.lax.scan(
                body, (list(p_list), list(m_list), amp_state),
                (x, y) if per_step_data else None,
                length=None if per_step_data else n_steps)
            return p, m, losses, jnp.sum(drops), amp

        rep = plan.replicated
        if per_step_data:
            dsh = NamedSharding(plan.mesh, PartitionSpec(
                None, *self.plan.batch_spec(len(data_shape) - 1)))
            lsh = NamedSharding(plan.mesh, PartitionSpec(
                None, *self.plan.batch_spec(len(label_shape) - 1)))
        else:
            dsh = plan.batch_sharding(len(data_shape))
            lsh = plan.batch_sharding(len(label_shape))
        amp_sh = (rep, rep, rep)
        in_shardings = (self._p_shardings, self._m_shardings, dsh, lsh,
                        amp_sh)
        out_shardings = (self._p_shardings, self._m_shardings, rep, rep,
                         amp_sh)
        donate = (0, 1) if self._donate else ()
        return jax.jit(many, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=donate)

    # -- the host API ------------------------------------------------------
    def _amp_state_in(self):
        if not self._amp:
            return (jnp.float32(1.0), jnp.float32(0.0), jnp.float32(0.0))
        return (self._scale, self._good, jnp.float32(0.0))

    def run_steps(self, data, label, n_steps: int = 1,
                  per_step_data: bool = False):
        """``n_steps`` fused training steps in ONE dispatch (lax.scan
        inside one jitted executable).  With ``per_step_data=True`` the
        inputs carry a leading ``n_steps`` axis consumed one batch per
        step.  Returns the per-step losses as a device array."""
        import time as _time
        d = jnp.asarray(data)
        l = jnp.asarray(label)
        if per_step_data and (d.shape[0] != n_steps
                              or l.shape[0] != n_steps):
            raise MXNetError(
                f"run_steps(per_step_data=True): leading axis must be "
                f"n_steps={n_steps}, got {d.shape}/{l.shape}")
        sig = (d.shape, str(d.dtype), l.shape, str(l.dtype),
               int(n_steps), bool(per_step_data))
        jitted = self._cache.get(sig)
        fresh = jitted is None
        if fresh:
            jitted = self._build(d.shape, str(d.dtype), l.shape,
                                 str(l.dtype), int(n_steps),
                                 per_step_data)
            self._cache[sig] = jitted
        tok = telemetry.begin_step()
        try:
            from .. import tracing
            with tracing.span("step.mesh4d_window",
                              n_steps=int(n_steps),
                              mesh=self.plan.describe()):
                tc = _time.perf_counter() if fresh else None
                with tracing.span("compile.spmd_step" if fresh
                                  else "step.dispatch"):
                    new_p, new_m, losses, dropped, amp = jitted(
                        self._params, self._momentum, d, l,
                        self._amp_state_in())
                    telemetry.record_dispatch()
                if tc is not None:
                    telemetry.record_compile(_time.perf_counter() - tc,
                                             "spmd_step")
                self._params = list(new_p)
                self._momentum = list(new_m)
                if self._amp:
                    self._scale, self._good = amp[0], amp[1]
                self.num_update += int(n_steps)
                self._account(int(n_steps),
                              d[0] if per_step_data else d)
                telemetry.record_moe_dropped(dropped)
        finally:
            telemetry.end_step(tok, "Mesh4DTrainer",
                               extra={"n_steps": int(n_steps)})
        return losses

    def step(self, data, label):
        """One training step; returns the scalar loss array."""
        return self.run_steps(data, label, n_steps=1)[0]

    # -- per-axis comm accounting ------------------------------------------
    def _account(self, n_steps: int, d) -> None:
        """Analytic per-axis wire attribution for one window (ring-cost
        model — GSPMD's collectives are inside the executable, so the
        model IS the accounting, same as the dp-only funnels):

        - dp: gradient reduce-scatter + master all-gather (ZeRO) or the
          folded allreduce, at the AMP wire itemsize on gradient legs.
        - tp: one activation partial-sum allreduce per tp-sharded
          matmul, forward + backward.
        - pp: each microbatch's activations ppermute S-1 hops forward
          and S-1 back.
        - ep: dispatch + combine all_to_all, forward + backward.
        """
        model = self._comm_model
        if model is None:
            from ..amp import policy as _amp_policy
            plan = self.plan
            isz = _amp_policy.compute_itemsize() if self._amp else 4
            gfrac = isz / 4.0
            model = {ax: 0 for ax in ("dp", "tp", "pp", "ep")}
            rs = ag = ar = 0
            for lf, spec, msh in zip(self._params, self._specs,
                                     self._m_shardings):
                nb = int(lf.nbytes)
                if plan.dp > 1:
                    if "dp" in MeshPlan._spec_axes(msh.spec):
                        rs += plan.ring_bytes(int(nb * gfrac), "dp",
                                              "reduce_scatter")
                        ag += plan.ring_bytes(nb, "dp", "all_gather")
                    else:
                        ar += plan.ring_bytes(int(nb * gfrac), "dp",
                                              "allreduce")
            model["dp"] = rs + ag + ar
            self._comm_split = (rs, ag, ar)
            # activation volume: one step's batch in compute-dtype
            # bytes (tokens × features) — coarse but stable
            act_elems = int(onp.prod(d.shape)) or 1
            act_bytes = act_elems * isz
            if plan.tp > 1:
                n_tp = sum(1 for s in self._specs
                           if "tp" in MeshPlan._spec_axes(s))
                model["tp"] = 2 * max(n_tp, 1) * plan.ring_bytes(
                    act_bytes, "tp", "allreduce")
            if plan.pp > 1:
                mb = act_bytes // max(self.n_microbatches, 1)
                model["pp"] = (2 * self.n_microbatches * (plan.pp - 1)
                               * plan.ring_bytes(mb, "pp", "ppermute"))
            if plan.ep > 1:
                model["ep"] = 4 * plan.ring_bytes(act_bytes, "ep",
                                                  "all_to_all")
            self._comm_model = model
        rs, ag, ar = self._comm_split
        if rs or ag:
            telemetry.record_comm_bytes(rs * n_steps, "reduce_scatter")
            telemetry.record_comm_bytes(ag * n_steps, "all_gather")
        if ar:
            telemetry.record_comm_bytes(ar * n_steps, "allreduce")
        if model["tp"]:
            telemetry.record_comm_bytes(model["tp"] * n_steps,
                                        "allreduce")
        if model["pp"]:
            telemetry.record_comm_bytes(model["pp"] * n_steps,
                                        "ppermute")
        if model["ep"]:
            telemetry.record_comm_bytes(model["ep"] * n_steps,
                                        "all_to_all")
        for ax, b in model.items():
            if b:
                telemetry.record_axis_comm_bytes(b * n_steps, ax)
        telemetry.record_opt_state_bytes(self.state_bytes_per_device(
            params=False))

    def state_bytes_per_device(self, params: bool = True) -> int:
        """Bytes of fp32 masters (+``params``) and momentum resident on
        the busiest device — the per-device memory the ZeRO×tp
        composition exists to shrink."""
        from ..optimizer.fused_step import opt_state_bytes_per_device
        arrays = list(self._momentum)
        if params:
            arrays += list(self._params)
        return opt_state_bytes_per_device(arrays)

    # -- checkpoint --------------------------------------------------------
    def save_checkpoint(self, directory, tag="latest", block=True):
        """fp32 masters + momentum through the async sharded checkpoint
        service.  The manifest records this plan's axis sizes as
        provenance; restore does NOT require them to match — shards
        reassemble to global arrays and re-place under the loading
        plan's composed shardings."""
        from .. import checkpoint as _ckpt
        tree = {}
        for i, (p, m) in enumerate(zip(self._params, self._momentum)):
            tree[f"param/{i}"] = p
            tree[f"momentum/{i}"] = m
        header = {"num_update": int(self.num_update),
                  "mesh_axes": self.plan.axis_sizes,
                  "n_leaves": len(self._params)}
        if self._amp:
            header["amp"] = {"scale": float(self._scale),
                             "good": float(self._good)}
        rank, world = _ckpt.rank_world()
        job = _ckpt.save(directory, tree, header, tag=tag, block=block,
                         rank=rank, world=world)
        return job.result() if block else job

    def load_checkpoint(self, directory, tag="latest"):
        """Restore a :meth:`save_checkpoint` snapshot onto THIS plan's
        shardings (any mesh shape — a dp2×tp2 save restores onto
        dp4×tp1 bit-identically).  Returns the header dict or None."""
        from .. import checkpoint as _ckpt
        loaded = _ckpt.load(directory, tag)
        if loaded is None:
            return None
        leaves, header = loaded
        n = int(header.get("n_leaves", len(self._params)))
        if n != len(self._params):
            raise MXNetError(
                f"checkpoint has {n} param leaves, trainer has "
                f"{len(self._params)}")
        for i in range(n):
            self._params[i] = jax.device_put(
                jnp.asarray(leaves[f"param/{i}"]), self._p_shardings[i])
            self._momentum[i] = jax.device_put(
                jnp.asarray(leaves[f"momentum/{i}"]),
                self._m_shardings[i])
        self.num_update = int(header.get("num_update", self.num_update))
        amp_hdr = header.get("amp")
        if amp_hdr and self._amp:
            self._scale = jnp.float32(amp_hdr.get("scale", 1.0))
            self._good = jnp.float32(amp_hdr.get("good", 0.0))
        return dict(header)
