"""Ring attention: sequence/context parallelism over a mesh axis.

Each device holds a sequence shard of Q, K, V.  K/V shards rotate
around the ring (`lax.ppermute` → XLA collective-permute riding ICI)
while every device folds the visiting block into flash-attention
online-softmax accumulators — attention over sequences far larger than
one chip's HBM, with compute/communication overlap handled by XLA's
async collectives.

The reference has no equivalent (SURVEY.md §5: "Long-context / sequence
parallelism: absent"); this is the capability the TPU build adds.
Expressed with `lax.scan` over ring steps so it is differentiable
(the transpose of ppermute is the reverse ppermute — backward runs the
ring the other way for free).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec
from ._shard_map_compat import shard_map

from ..ops.attention import online_block_update, _NEG_INF

__all__ = ["ring_attention", "ring_self_attention",
           "ring_flash_attention", "ring_flash_self_attention",
           "seq_shard_call"]


def seq_shard_call(body, mesh: Mesh, axis_name: str, q, k, v,
                   check_vma: bool = False):
    """Shared wrapper for the sequence-parallel attention schemes:
    shard the S axis of (B, H, S, D) tensors over ``axis_name`` and run
    ``body(q, k, v)`` under shard_map.  The device_put is a sharding
    constraint under jit; eagerly (e.g. a deferred-init warm-up
    forward) it moves single-device arrays onto the mesh so shard_map
    accepts them either way."""
    spec = PartitionSpec(None, None, axis_name, None)
    sh = jax.sharding.NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(q, sh), jax.device_put(k, sh),
               jax.device_put(v, sh))
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=check_vma)(q, k, v)


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Per-shard ring attention body; call inside shard_map/pjit.

    q: (B, H, S_local, D); k, v: (B, Hkv, S_local, D) — this device's
    sequence shard.  GQA/MQA: with Hkv < H the SMALL K/V blocks rotate
    around the ring (minimal collective-permute traffic) and are
    broadcast to the query groups only at each local block update.
    Returns the local output shard (B, H, S_local, D).
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    if hkv <= 0 or h % hkv:
        raise ValueError(f"q heads ({h}) not divisible by kv heads "
                         f"({hkv})")
    group = h // hkv
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    q32 = q.astype(jnp.float32)
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, ring_step):
        o, m, l, kc, vc = carry
        kv_idx = (my - ring_step) % n

        def update(o, m, l):
            mask = None
            if causal:
                qpos = (my * sq
                        + lax.broadcasted_iota(jnp.int32, (b, h, sq, sk), 2))
                kpos = (kv_idx * sk
                        + lax.broadcasted_iota(jnp.int32, (b, h, sq, sk), 3))
                mask = qpos >= kpos
            ke = jnp.repeat(kc, group, axis=1) if group > 1 else kc
            ve = jnp.repeat(vc, group, axis=1) if group > 1 else vc
            return online_block_update(o, m, l, q32, ke, ve, scale, mask)

        if causal:
            # shards strictly above the diagonal contribute nothing —
            # skip both matmuls, keep only the ring rotation
            o, m, l = lax.cond(kv_idx <= my, update,
                               lambda o, m, l: (o, m, l), o, m, l)
        else:
            o, m, l = update(o, m, l)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32)),
        jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l).astype(q.dtype)


# --------------------------------------------------------------------------
# Ring FLASH attention: the visiting K/V shard is consumed by the
# Pallas flash kernel (scores never materialize in HBM — VMEM-blocked),
# and per-shard (out, lse) pairs merge in log-sum-exp space.  The
# backward is the ring-flash scheme: re-run the ring with the FINAL lse
# (flash semantics: p = exp(s_block - lse_final)), accumulate dq
# locally while dk/dv accumulators ride the rotating K/V buffers so
# each shard's gradient arrives home after the full cycle.
#
# vs `ring_attention` above: that path materializes each local
# (S_q x S_k) f32 score block per ring step; this one keeps the block
# math inside the flash kernel.  GQA note: K/V are expanded to the
# query head count BEFORE the ring here, so rotation traffic is
# group x larger than ring_attention's small-KV rotation — prefer
# ring_attention for extreme GQA ratios, ring_flash_attention for
# long-context dense/moderate-GQA attention.
# --------------------------------------------------------------------------

def _merge_lse(o, lse, ob, lseb):
    """Combine two normalized partial attentions in logsumexp space."""
    new = jnp.logaddexp(lse, lseb)
    w1 = jnp.exp(lse - new)[..., None]
    w2 = jnp.exp(lseb - new)[..., None]
    return o * w1 + ob.astype(o.dtype) * w2, new


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, bq, bk):
    from ..ops.attention import _fa_forward_pallas

    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qf = q.reshape(b * h, sq, d)
    o0 = jnp.zeros((b * h, sq, d), jnp.float32)
    lse0 = jnp.full((b * h, sq), _NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        o, lse, kc, vc = carry
        kf = kc.reshape(b * h, sk, d)
        vf = vc.reshape(b * h, sk, d)

        def full_block(o, lse):
            ob, lb = _fa_forward_pallas(qf, kf, vf, False, scale, bq, bk)
            return _merge_lse(o, lse, ob, lb)

        def diag_block(o, lse):
            ob, lb = _fa_forward_pallas(qf, kf, vf, True, scale, bq, bk)
            return _merge_lse(o, lse, ob, lb)

        if causal:
            kv_idx = (my - t) % n
            o, lse = lax.cond(
                kv_idx > my, lambda o, l: (o, l),
                lambda o, l: lax.cond(kv_idx == my, diag_block,
                                      full_block, o, l), o, lse)
        else:
            o, lse = full_block(o, lse)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, lse, kc, vc), None

    (o, lse, _, _), _ = lax.scan(step, (o0, lse0, k, v),
                                 jnp.arange(n))
    return o.reshape(b, h, sq, d).astype(q.dtype), lse


def _ring_flash_bwd_impl(q, k, v, out, lse, do, axis_name, causal,
                         scale, bq, bk):
    from ..ops.attention import _fa_backward_pallas

    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    qf = q.reshape(b * h, sq, d)
    outf = out.reshape(b * h, sq, d)
    dof = do.reshape(b * h, sq, d)
    perm = [(i, (i + 1) % n) for i in range(n)]
    dq0 = jnp.zeros((b * h, sq, d), jnp.float32)
    # delta is loop-invariant (do/out fixed across ring steps): hoist
    delta = jnp.sum(dof.astype(jnp.float32) * outf.astype(jnp.float32),
                    axis=-1)

    def step(carry, t):
        dq, dkc, dvc, kc, vc = carry
        kf = kc.reshape(b * h, sk, d)
        vf = vc.reshape(b * h, sk, d)

        def grads(block_causal):
            def run(_):
                # flash backward against the GLOBAL lse: per-block
                # p = exp(s_b - lse_final) is exactly this block's
                # share of the final attention
                return _fa_backward_pallas(
                    block_causal, scale, bq, bk,
                    (qf, kf, vf, outf, lse), dof, delta=delta)
            return run

        zero = lambda _: (jnp.zeros_like(qf), jnp.zeros_like(kf),
                          jnp.zeros_like(vf))
        if causal:
            kv_idx = (my - t) % n
            dqb, dkb, dvb = lax.cond(
                kv_idx > my, zero,
                lambda u: lax.cond(kv_idx == my, grads(True),
                                   grads(False), u), 0)
        else:
            dqb, dkb, dvb = grads(False)(0)
        dq = dq + dqb.astype(jnp.float32)
        dkc = dkc + dkb.astype(jnp.float32).reshape(dkc.shape)
        dvc = dvc + dvb.astype(jnp.float32).reshape(dvc.shape)
        # gradients ride home with their shards
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dkc = lax.ppermute(dkc, axis_name, perm)
        dvc = lax.ppermute(dvc, axis_name, perm)
        return (dq, dkc, dvc, kc, vc), None

    init = (dq0, jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32), k, v)
    (dq, dk, dv, _, _), _ = lax.scan(step, init, jnp.arange(n))
    return (dq.reshape(b, h, sq, d).astype(q.dtype),
            dk.astype(k.dtype), dv.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis_name, causal, scale, bq, bk):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                  bq, bk)
    return out


def _ring_flash_f(q, k, v, axis_name, causal, scale, bq, bk):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                    bq, bk)
    return out, (q, k, v, out, lse)


def _ring_flash_b(axis_name, causal, scale, bq, bk, res, do):
    q, k, v, out, lse = res
    return _ring_flash_bwd_impl(q, k, v, out, lse, do, axis_name,
                                causal, scale, bq, bk)


_ring_flash.defvjp(_ring_flash_f, _ring_flash_b)


def ring_flash_attention(q, k, v, axis_name: str = "sp",
                         causal: bool = False,
                         sm_scale: Optional[float] = None,
                         block_q: Optional[int] = None,
                         block_k: Optional[int] = None):
    """Per-shard ring attention with the Pallas flash kernel as the
    local block engine; call inside shard_map/pjit.  Same contract as
    :func:`ring_attention` for equal q/k shard lengths (GQA K/V are
    expanded to the query head count first — see the traffic note
    above); causal mode requires sq == sk per shard (the shard-index
    classification assumes aligned positions — use ring_attention for
    causal cross-attention over unequal shards).  Block sizes default
    to the env-tunable MXNET_TPU_FLASH_BLOCK_Q/_K like
    flash_attention."""
    from ..ops.attention import _flash_block_default

    b, h, sq, d = q.shape
    hkv = k.shape[1]
    if hkv <= 0 or h % hkv:
        raise ValueError(f"q heads ({h}) not divisible by kv heads "
                         f"({hkv})")
    if causal and sq != k.shape[2]:
        raise ValueError(
            f"ring_flash_attention(causal=True) needs equal per-shard "
            f"q/k lengths (got {sq} vs {k.shape[2]}); ring_attention "
            f"handles causal cross-attention over unequal shards")
    if hkv != h:
        rep = h // hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    if block_q is None:
        block_q = _flash_block_default("Q")
    if block_k is None:
        block_k = _flash_block_default("K")
    return _ring_flash(q, k, v, axis_name, causal, scale, block_q,
                       block_k)


def ring_flash_self_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                              causal: bool = False,
                              sm_scale: Optional[float] = None,
                              block_q: Optional[int] = None,
                              block_k: Optional[int] = None):
    """shard_map wrapper for :func:`ring_flash_attention`."""
    fn = functools.partial(ring_flash_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale,
                           block_q=block_q, block_k=block_k)
    return seq_shard_call(fn, mesh, axis_name, q, k, v)


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                        causal: bool = False,
                        sm_scale: Optional[float] = None):
    """shard_map wrapper: shards the sequence axis of (B,H,S,D) over
    ``axis_name`` and runs ring attention across the mesh."""
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    return seq_shard_call(fn, mesh, axis_name, q, k, v)
