"""Ring attention: sequence/context parallelism over a mesh axis.

Each device holds a sequence shard of Q, K, V.  K/V shards rotate
around the ring (`lax.ppermute` → XLA collective-permute riding ICI)
while every device folds the visiting block into flash-attention
online-softmax accumulators — attention over sequences far larger than
one chip's HBM, with compute/communication overlap handled by XLA's
async collectives.

The reference has no equivalent (SURVEY.md §5: "Long-context / sequence
parallelism: absent"); this is the capability the TPU build adds.
Expressed with `lax.scan` over ring steps so it is differentiable
(the transpose of ppermute is the reverse ppermute — backward runs the
ring the other way for free).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec
from jax import shard_map

from ..ops.attention import online_block_update, _NEG_INF

__all__ = ["ring_attention", "ring_self_attention"]


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Per-shard ring attention body; call inside shard_map/pjit.

    q: (B, H, S_local, D); k, v: (B, Hkv, S_local, D) — this device's
    sequence shard.  GQA/MQA: with Hkv < H the SMALL K/V blocks rotate
    around the ring (minimal collective-permute traffic) and are
    broadcast to the query groups only at each local block update.
    Returns the local output shard (B, H, S_local, D).
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    hkv = k.shape[1]
    if hkv <= 0 or h % hkv:
        raise ValueError(f"q heads ({h}) not divisible by kv heads "
                         f"({hkv})")
    group = h // hkv
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    q32 = q.astype(jnp.float32)
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, ring_step):
        o, m, l, kc, vc = carry
        kv_idx = (my - ring_step) % n

        def update(o, m, l):
            mask = None
            if causal:
                qpos = (my * sq
                        + lax.broadcasted_iota(jnp.int32, (b, h, sq, sk), 2))
                kpos = (kv_idx * sk
                        + lax.broadcasted_iota(jnp.int32, (b, h, sq, sk), 3))
                mask = qpos >= kpos
            ke = jnp.repeat(kc, group, axis=1) if group > 1 else kc
            ve = jnp.repeat(vc, group, axis=1) if group > 1 else vc
            return online_block_update(o, m, l, q32, ke, ve, scale, mask)

        if causal:
            # shards strictly above the diagonal contribute nothing —
            # skip both matmuls, keep only the ring rotation
            o, m, l = lax.cond(kv_idx <= my, update,
                               lambda o, m, l: (o, m, l), o, m, l)
        else:
            o, m, l = update(o, m, l)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32)),
        jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l).astype(q.dtype)


def ring_self_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                        causal: bool = False,
                        sm_scale: Optional[float] = None):
    """shard_map wrapper: shards the sequence axis of (B,H,S,D) over
    ``axis_name`` and runs ring attention across the mesh."""
    spec = PartitionSpec(None, None, axis_name, None)
    # place inputs onto the mesh first: under jit this is a sharding
    # constraint; eagerly (e.g. a deferred-init warm-up forward) it
    # moves the single-device array onto the mesh so shard_map accepts
    # it either way
    sh = jax.sharding.NamedSharding(mesh, spec)
    q, k, v = (jax.device_put(q, sh), jax.device_put(k, sh),
               jax.device_put(v, sh))
    fn = functools.partial(ring_attention, axis_name=axis_name,
                           causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
