"""Device mesh helpers.

The axis-name convention (used across the framework):
  dp — data parallel, tp — tensor/model parallel, pp — pipeline,
  sp — sequence/context parallel, ep — expert parallel.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as onp
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "default_mesh", "data_parallel_spec", "replicated"]


def make_mesh(axes: Dict[str, int] | None = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh; axes maps axis-name → size (-1 = fill remaining).

    ``make_mesh({"dp": -1})`` → 1-D data-parallel mesh over all devices;
    ``make_mesh({"dp": 2, "tp": 4})`` → 2×4.
    """
    devices = list(devices) if devices is not None else jax.devices()
    axes = dict(axes or {"dp": -1})
    sizes = list(axes.values())
    n = len(devices)
    if -1 in sizes:
        known = 1
        for s in sizes:
            if s != -1:
                known *= s
        sizes[sizes.index(-1)] = max(n // known, 1)
    total = 1
    for s in sizes:
        total *= s
    if total > n:
        raise ValueError(f"mesh {dict(zip(axes, sizes))} needs {total} "
                         f"devices, have {n}")
    dev_array = onp.array(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


_default: Optional[Mesh] = None


def default_mesh() -> Mesh:
    global _default
    if _default is None:
        _default = make_mesh({"dp": -1})
    return _default


def data_parallel_spec(mesh: Mesh, batch_axis: int = 0,
                       ndim: int = 2) -> NamedSharding:
    """Sharding for a batch tensor: batch axis split over 'dp'."""
    spec = [None] * ndim
    spec[batch_axis] = "dp"
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
