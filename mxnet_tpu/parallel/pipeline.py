"""Pipeline parallelism: GPipe-style stage execution over a mesh axis.

TPU-native design (no reference counterpart to translate: the
reference's "model parallelism" is per-layer ctx placement,
`group2ctxs` in graph_executor.cc — a host-scheduled form the compiler
replaces here): stages live one-per-device along a ``pp`` mesh axis,
microbatches stream through, and stage outputs hop to the next device
with `lax.ppermute` (XLA collective-permute over ICI).  Expressed so
`jax.grad` differentiates straight through — the transpose of ppermute
is the reverse ppermute, so the backward pipeline runs automatically in
the opposite direction.

Layout: stage parameters are stacked on a leading axis sharded over
``pp``; inside `shard_map` each device sees only its own stage's
params.  The schedule is the classic GPipe fill-drain: with S stages
and M microbatches the loop runs S+M-1 ticks at 1/S bubble overhead.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec
from jax import shard_map

__all__ = ["gpipe_apply", "pipeline_forward"]


def gpipe_apply(stage_fn: Callable, n_stages: int, axis_name: str = "pp"):
    """Build the per-device pipeline body; call inside shard_map.

    ``stage_fn(stage_params, x) -> y`` is one stage's computation; every
    stage must map shape (mb, ...) -> (mb, ...) identically (uniform
    pipelines — the GPipe assumption).

    Returns ``apply(stage_params, x_microbatches)`` where
    ``stage_params`` is this device's stage slice and
    ``x_microbatches`` has shape (M, mb, ...).  The result is the
    last stage's outputs, (M, mb, ...), replicated over the axis.
    """
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def apply(stage_params, x_mb):
        idx = lax.axis_index(axis_name)
        M = x_mb.shape[0]
        carry = jnp.zeros_like(x_mb[0])
        out = jnp.zeros_like(x_mb)
        for t in range(n_stages + M - 1):
            feed = x_mb[min(t, M - 1)]
            inp = jnp.where(idx == 0, feed, carry)
            y = stage_fn(stage_params, inp)
            # collect on the last stage: at tick t it finishes
            # microbatch t-(S-1)
            m = t - (n_stages - 1)
            if m >= 0:
                write = jnp.where(idx == n_stages - 1, y, out[m])
                out = out.at[m].set(write)
            carry = lax.ppermute(y, axis_name, perm)
        # replicate the collected outputs (they live on the last stage)
        mask = (idx == n_stages - 1).astype(out.dtype)
        return lax.psum(out * mask, axis_name)

    return apply


def pipeline_forward(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                     n_microbatches: int, axis_name: str = "pp",
                     batch_axis_name: Optional[str] = "dp"):
    """Run a full pipeline forward over a mesh (convenience wrapper).

    ``stacked_params``: pytree whose leaves have a leading stage axis of
    size mesh.shape[axis_name] (sharded over it).  ``x``: (B, ...) batch
    — split into ``n_microbatches`` along axis 0; if the mesh also has
    ``batch_axis_name``, the batch dim is additionally sharded over it
    (dp×pp).  Returns (B, ...) outputs with the same sharding as x.
    """
    S = mesh.shape[axis_name]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"pipeline_forward: param leading (stage) axis "
                f"{leaf.shape[0]} != pp mesh size {S} — one stage per "
                f"device (stack multiple layers inside stage_fn instead)")
    body = gpipe_apply(stage_fn, S, axis_name)
    dp = (batch_axis_name
          if batch_axis_name and batch_axis_name in mesh.axis_names
          else None)
    n_dp = mesh.shape[dp] if dp else 1
    if x.shape[0] % (n_dp * n_microbatches):
        raise ValueError(
            f"pipeline_forward: batch {x.shape[0]} not divisible by "
            f"dp({n_dp}) x n_microbatches({n_microbatches})")

    def full(params, xb):
        # shard_map keeps the sharded stage axis at local size 1 — drop it
        local = jax.tree.map(lambda a: a[0], params)
        M = n_microbatches
        xmb = xb.reshape((M, xb.shape[0] // M) + xb.shape[1:])
        out = body(local, xmb)
        return out.reshape(xb.shape[0:1] + out.shape[2:])

    pspec = jax.tree.map(lambda _: PartitionSpec(axis_name), stacked_params)
    xspec = PartitionSpec(dp)
    return shard_map(full, mesh=mesh, in_specs=(pspec, xspec),
                     out_specs=xspec, check_vma=False)(stacked_params, x)
