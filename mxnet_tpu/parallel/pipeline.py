"""Pipeline parallelism: GPipe-style stage execution over a mesh axis.

TPU-native design (no reference counterpart to translate: the
reference's "model parallelism" is per-layer ctx placement,
`group2ctxs` in graph_executor.cc — a host-scheduled form the compiler
replaces here): stages live one-per-device along a ``pp`` mesh axis,
microbatches stream through, and stage outputs hop to the next device
with `lax.ppermute` (XLA collective-permute over ICI).  Expressed so
`jax.grad` differentiates straight through — the transpose of ppermute
is the reverse ppermute, so the backward pipeline runs automatically in
the opposite direction.

Layout: stage parameters are stacked on a leading axis sharded over
``pp``; inside `shard_map` each device sees only its own stage's
params.  The schedule is the classic GPipe fill-drain: with S stages
and M microbatches the loop runs S+M-1 ticks at 1/S bubble overhead.
"""
from __future__ import annotations

import warnings
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec
from ._shard_map_compat import shard_map

__all__ = ["gpipe_apply", "pipeline_forward", "interleaved_apply",
           "pipeline_forward_interleaved", "pipeline_forward_1f1b",
           "interleave_params", "interleaved_ticks", "gpipe_ticks",
           "one_f_one_b_apply", "pipeline_value_and_grad_1f1b",
           "one_f_one_b_ticks"]


def gpipe_apply(stage_fn: Callable, n_stages: int, axis_name: str = "pp"):
    """Build the per-device pipeline body; call inside shard_map.

    ``stage_fn(stage_params, x) -> y`` is one stage's computation; every
    stage must map shape (mb, ...) -> (mb, ...) identically (uniform
    pipelines — the GPipe assumption).

    Returns ``apply(stage_params, x_microbatches)`` where
    ``stage_params`` is this device's stage slice and
    ``x_microbatches`` has shape (M, mb, ...).  The result is the
    last stage's outputs, (M, mb, ...), replicated over the axis.
    """
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def apply(stage_params, x_mb):
        idx = lax.axis_index(axis_name)
        M = x_mb.shape[0]
        carry = jnp.zeros_like(x_mb[0])
        out = jnp.zeros_like(x_mb)
        for t in range(n_stages + M - 1):
            feed = x_mb[min(t, M - 1)]
            inp = jnp.where(idx == 0, feed, carry)
            y = stage_fn(stage_params, inp)
            # collect on the last stage: at tick t it finishes
            # microbatch t-(S-1)
            m = t - (n_stages - 1)
            if m >= 0:
                write = jnp.where(idx == n_stages - 1, y, out[m])
                out = out.at[m].set(write)
            carry = lax.ppermute(y, axis_name, perm)
        # replicate the collected outputs (they live on the last stage)
        mask = (idx == n_stages - 1).astype(out.dtype)
        return lax.psum(out * mask, axis_name)

    return apply


def pipeline_forward(stage_fn: Callable, stacked_params, x, mesh: Mesh,
                     n_microbatches: int, axis_name: str = "pp",
                     batch_axis_name: Optional[str] = "dp"):
    """Run a full pipeline forward over a mesh (convenience wrapper).

    ``stacked_params``: pytree whose leaves have a leading stage axis of
    size mesh.shape[axis_name] (sharded over it).  ``x``: (B, ...) batch
    — split into ``n_microbatches`` along axis 0; if the mesh also has
    ``batch_axis_name``, the batch dim is additionally sharded over it
    (dp×pp).  Returns (B, ...) outputs with the same sharding as x.
    """
    S = mesh.shape[axis_name]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"pipeline_forward: param leading (stage) axis "
                f"{leaf.shape[0]} != pp mesh size {S} — one stage per "
                f"device (stack multiple layers inside stage_fn instead)")
    body = gpipe_apply(stage_fn, S, axis_name)
    dp = (batch_axis_name
          if batch_axis_name and batch_axis_name in mesh.axis_names
          else None)
    n_dp = mesh.shape[dp] if dp else 1
    if x.shape[0] % (n_dp * n_microbatches):
        raise ValueError(
            f"pipeline_forward: batch {x.shape[0]} not divisible by "
            f"dp({n_dp}) x n_microbatches({n_microbatches})")

    def full(params, xb):
        # shard_map keeps the sharded stage axis at local size 1 — drop it
        local = jax.tree.map(lambda a: a[0], params)
        M = n_microbatches
        xmb = xb.reshape((M, xb.shape[0] // M) + xb.shape[1:])
        out = body(local, xmb)
        return out.reshape(xb.shape[0:1] + out.shape[2:])

    pspec = jax.tree.map(lambda _: PartitionSpec(axis_name), stacked_params)
    xspec = PartitionSpec(dp)
    return shard_map(full, mesh=mesh, in_specs=(pspec, xspec),
                     out_specs=xspec, check_vma=False)(stacked_params, x)


# --------------------------------------------------------------------------
# Interleaved 1F1B-style schedule (virtual stages).  The reference has no
# pipeline parallelism at all (its model parallelism is per-layer ctx
# placement, docs model_parallel_lstm.md) — this is north-star scaling
# work per SURVEY §7.
#
# Device d holds V *virtual* stages: layers {j*S + d, j=0..V-1}.  A
# microbatch circulates V times around the pp ring, so the fill/drain
# bubble shrinks from GPipe's (S-1)/(S+M-1) of step time to
# (S-1)/(V*S+M-1) — at M=S=4, V=2 that is 27% vs 43%.  Because the
# whole schedule is one differentiable loop of ppermutes, jax.grad
# produces the mirrored backward schedule automatically (the transpose
# of ppermute is the reverse ppermute).
# --------------------------------------------------------------------------

def interleaved_ticks(n_stages: int, n_virtual: int,
                      n_microbatches: int) -> int:
    """Total schedule ticks (per-device time in single-layer units)."""
    return n_virtual * n_stages + n_microbatches - 1


def gpipe_ticks(n_stages: int, n_virtual: int, n_microbatches: int) -> int:
    """GPipe per-device time in the same units: each of the S+M-1 ticks
    runs all V layers the device owns."""
    return n_virtual * (n_stages + n_microbatches - 1)


def interleaved_apply(stage_fn: Callable, n_stages: int, n_virtual: int,
                      axis_name: str = "pp"):
    """Per-device body of the interleaved pipeline; call inside shard_map.

    ``stage_fn(layer_params, x) -> y`` is ONE layer (virtual stage);
    uniform shapes.  Returns ``apply(vstage_params, x_microbatches)``
    where ``vstage_params`` has leading axis V (this device's virtual
    stages, ring order: global layer j*S + d) and ``x_microbatches`` is
    (M, mb, ...) with M <= S (the small-microbatch regime interleaving
    exists for; larger M would collide two microbatches on one device
    in the same tick).
    """
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def apply(vstage_params, x_mb):
        idx = lax.axis_index(axis_name)
        M = x_mb.shape[0]
        if M > n_stages:
            raise ValueError(
                f"interleaved schedule needs M <= S (got M={M}, "
                f"S={n_stages}); use gpipe_apply for deep microbatching")
        V = n_virtual
        T = interleaved_ticks(n_stages, V, M)
        carry = jnp.zeros_like(x_mb[0])
        out = jnp.zeros_like(x_mb)
        for t in range(T):
            # device d at tick t serves round j = (t - d) // S; clip to
            # the valid range (out-of-range ticks are bubble — the
            # computed garbage is never routed into an output)
            j = jnp.clip((t - idx) // n_stages, 0, V - 1)
            params_t = jax.tree.map(lambda a: a[j], vstage_params)
            feed = x_mb[min(t, M - 1)]
            inp = jnp.where((idx == 0) & (t < M), feed, carry)
            y = stage_fn(params_t, inp)
            m = t - (V * n_stages - 1)
            if m >= 0:
                write = jnp.where(idx == n_stages - 1, y, out[m])
                out = out.at[m].set(write)
            carry = lax.ppermute(y, axis_name, perm)
        mask = (idx == n_stages - 1).astype(out.dtype)
        return lax.psum(out * mask, axis_name)

    return apply


def interleave_params(layer_params, n_stages: int):
    """Rearrange a (L, ...) layer stack into the interleaved layout
    (S, V, ...): device d's round j applies global layer j*S + d."""
    def rearrange(a):
        L = a.shape[0]
        if L % n_stages:
            raise ValueError(
                f"layer count {L} not divisible by pp size {n_stages}")
        V = L // n_stages
        # index [d, j] -> layer j*S + d
        idx = (jnp.arange(V)[None, :] * n_stages
               + jnp.arange(n_stages)[:, None])
        return a[idx.reshape(-1)].reshape((n_stages, V) + a.shape[1:])
    return jax.tree.map(rearrange, layer_params)


def pipeline_forward_interleaved(stage_fn: Callable, layer_params, x,
                                 mesh: Mesh, n_microbatches: int,
                                 axis_name: str = "pp",
                                 batch_axis_name: Optional[str] = "dp"):
    """Interleaved-GPipe pipeline forward (virtual stages, fill-drain).

    Cuts the schedule bubble from GPipe's (S-1)/(S+M-1) to
    (S-1)/(V*S+M-1) by circulating each microbatch V times around the
    ring.  NOTE: this is a *forward* whose backward (under ``jax.grad``)
    replays after the whole forward, so all M microbatches' activations
    stay live — it does NOT have true 1F1B's O(S) activation bound.  For
    the activation-bounded schedule use
    :func:`pipeline_value_and_grad_1f1b`.

    ``layer_params``: pytree with leading axis L = V*S (the plain layer
    stack, in network order); rearranged internally to the interleaved
    placement.  Same contract as :func:`pipeline_forward` otherwise.
    """
    S = mesh.shape[axis_name]
    L = jax.tree.leaves(layer_params)[0].shape[0]
    V = L // S
    if L % S:
        raise ValueError(
            f"interleaved: layer count {L} not divisible by S={S}")
    inter = interleave_params(layer_params, S)
    body = interleaved_apply(stage_fn, S, V, axis_name)
    dp = (batch_axis_name
          if batch_axis_name and batch_axis_name in mesh.axis_names
          else None)
    n_dp = mesh.shape[dp] if dp else 1
    if x.shape[0] % (n_dp * n_microbatches):
        raise ValueError(
            f"interleaved: batch {x.shape[0]} not divisible by dp({n_dp}) "
            f"x n_microbatches({n_microbatches})")

    def full(params, xb):
        local = jax.tree.map(lambda a: a[0], params)   # drop sharded S
        M = n_microbatches
        xmb = xb.reshape((M, xb.shape[0] // M) + xb.shape[1:])
        out = body(local, xmb)
        return out.reshape(xb.shape[0:1] + out.shape[2:])

    pspec = jax.tree.map(lambda _: PartitionSpec(axis_name), inter)
    xspec = PartitionSpec(dp)
    return shard_map(full, mesh=mesh, in_specs=(pspec, xspec),
                     out_specs=xspec, check_vma=False)(inter, x)


def pipeline_forward_1f1b(*args, **kwargs):
    """Deprecated alias for :func:`pipeline_forward_interleaved`.

    The schedule it runs is interleaved fill-drain (smaller bubble), not
    activation-bounded 1F1B; the honest name is ``interleaved``.  For
    the true 1F1B training step see :func:`pipeline_value_and_grad_1f1b`.
    """
    warnings.warn(
        "pipeline_forward_1f1b is renamed pipeline_forward_interleaved "
        "(it is an interleaved fill-drain schedule, not activation-"
        "bounded 1F1B); for true 1F1B use pipeline_value_and_grad_1f1b",
        DeprecationWarning, stacklevel=2)
    return pipeline_forward_interleaved(*args, **kwargs)


# --------------------------------------------------------------------------
# True 1F1B: activation-bounded forward/backward interleaving.
#
# The defining property of 1F1B (PipeDream-flush / Megatron-LM's
# schedule) is that backward work for microbatch m starts as soon as its
# forward clears the last stage, so each device holds activations for at
# most O(S) in-flight microbatches — NOT O(M) as in GPipe-under-
# ``jax.grad`` (whose backward replays only after the entire forward).
#
# SPMD formulation: one `lax.scan` over T = M + 2S - 2 ticks.  Every
# tick each device runs one forward slot (microbatch  mf = t - s  when
# valid) and one backward slot (microbatch  mb = t - (2S-2) + s).
# Activations hop +1 on the ring after the F slot, cotangents hop -1
# after the B slot.  The last stage seeds each microbatch's cotangent
# from the loss the same tick its forward lands (B(S-1,m) shares tick
# m+S-1 with F(S-1,m)).
#
# Memory: the only cross-tick activation state is a stash of *stage
# inputs*, one slot per in-flight microbatch — a ring buffer of
# W = min(2S-1, M) entries (stage s holds at most 2S-1-2s in flight;
# entry m is written at tick m+s and read at tick m+2S-2-s, so W=2S-1
# slots never collide).  The backward slot recomputes its stage forward
# from the stashed input (``jax.vjp`` at backward time) — the standard
# remat trade: each tick costs 2f+b instead of f+b, identical to what
# GPipe-under-grad pays once ``jax.checkpoint`` is on, but with the
# activation working set O(S·|input|) instead of O(M·|residuals|).
# This is what unlocks deep microbatching (M >> S): bubble fraction
# (2S-2)/(M+2S-2) -> 0 while memory stays flat in M
# (pinned by tests/test_parallel_extra.py memory-growth test).
#
# The reference has no pipeline parallelism at all (its model
# parallelism is per-layer ctx placement, docs model_parallel_lstm.md);
# this is north-star scaling work per SURVEY §7.
# --------------------------------------------------------------------------

def one_f_one_b_ticks(n_stages: int, n_microbatches: int) -> int:
    """Total 1F1B schedule ticks; each tick is one F slot + one B slot."""
    return n_microbatches + 2 * n_stages - 2


def one_f_one_b_apply(stage_fn: Callable, loss_fn: Callable, n_stages: int,
                      n_microbatches: int, axis_name: str = "pp",
                      return_input_grad: bool = False):
    """Per-device 1F1B training-step body; call inside shard_map.

    ``stage_fn(stage_params, x) -> y`` is one stage (uniform shapes);
    ``loss_fn(y, target) -> scalar`` is applied to the last stage's
    output per microbatch.  Returns ``apply(stage_params, x_mb, t_mb)``
    -> ``(mean_loss, grads)`` where ``x_mb``/``t_mb`` are (M, mb, ...)
    microbatches and ``grads`` matches ``stage_params`` (this device's
    stage only; loss is replicated over the axis).  With
    ``return_input_grad`` the result is ``(loss, grads, dx_mb)`` where
    ``dx_mb`` is d(loss)/d(x_mb) — stage 0 collects its backward-slot
    input cotangents per microbatch (for chaining e.g. an embedding
    lookup in front of the pipeline).
    """
    S, M = n_stages, n_microbatches
    W = min(2 * S - 1, M)          # stash ring-buffer slots (O(S), not O(M))
    T = one_f_one_b_ticks(S, M)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]

    def apply(stage_params, x_mb, t_mb):
        idx = lax.axis_index(axis_name)
        carry_f = jnp.zeros_like(x_mb[0])
        stash = jnp.zeros((W,) + x_mb.shape[1:], x_mb.dtype)
        # probe the output/cotangent shape once (abstract eval only)
        y_shape = jax.eval_shape(stage_fn, stage_params, x_mb[0])
        carry_b = jnp.zeros(y_shape.shape, y_shape.dtype)
        grads0 = jax.tree.map(jnp.zeros_like, stage_params)
        loss0 = jnp.zeros((), jnp.float32)
        dx0 = jnp.zeros_like(x_mb) if return_input_grad else \
            jnp.zeros((), x_mb.dtype)

        def tick(carry, t):
            carry_f, carry_b, stash, grads, loss_acc, dx_acc = carry
            # ---- F slot: microbatch mf = t - idx flows GPipe-style
            mf = t - idx
            valid_f = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            feed = lax.dynamic_index_in_dim(x_mb, mf_c, 0, keepdims=False)
            inp = jnp.where(idx == 0, feed, carry_f)
            slot_f = mf_c % W
            old = lax.dynamic_index_in_dim(stash, slot_f, 0, keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(valid_f, inp, old), slot_f, 0)
            y = stage_fn(stage_params, inp)
            new_carry_f = lax.ppermute(y, axis_name, fwd_perm)
            # ---- B slot: microbatch mb = t - (2S-2) + idx drains the ring
            mb = t - (2 * S - 2) + idx
            valid_b = (mb >= 0) & (mb < M)
            mb_c = jnp.clip(mb, 0, M - 1)
            xin = lax.dynamic_index_in_dim(stash, mb_c % W, 0,
                                           keepdims=False)
            y2, vjp_fn = jax.vjp(stage_fn, stage_params, xin)
            tgt = lax.dynamic_index_in_dim(t_mb, mb_c, 0, keepdims=False)
            loss_m, dldy = jax.value_and_grad(
                lambda yy: loss_fn(yy, tgt))(y2)
            # last stage seeds from the loss; others consume the ring
            cot = jnp.where(idx == S - 1, (dldy / M).astype(y2.dtype),
                            carry_b)
            dparams, dx = vjp_fn(cot)
            grads = jax.tree.map(
                lambda g, d: g + jnp.where(valid_b, d, jnp.zeros_like(d)),
                grads, dparams)
            loss_acc = loss_acc + jnp.where(
                valid_b & (idx == S - 1), loss_m / M, 0.0).astype(
                    jnp.float32)
            if return_input_grad:
                # stage 0's backward-slot dx IS d(loss)/d(x_mb[mb])
                slot = mb_c
                old_dx = lax.dynamic_index_in_dim(dx_acc, slot, 0,
                                                  keepdims=False)
                dx_acc = lax.dynamic_update_index_in_dim(
                    dx_acc,
                    jnp.where(valid_b & (idx == 0),
                              dx.astype(dx_acc.dtype), old_dx),
                    slot, 0)
            new_carry_b = lax.ppermute(dx, axis_name, bwd_perm)
            return (new_carry_f, new_carry_b, stash, grads, loss_acc,
                    dx_acc), None

        (_, _, _, grads, loss_acc, dx_acc), _ = lax.scan(
            tick, (carry_f, carry_b, stash, grads0, loss0, dx0),
            jnp.arange(T))
        mask = (idx == S - 1).astype(loss_acc.dtype)
        loss = lax.psum(loss_acc * mask, axis_name)
        if return_input_grad:
            # dx lives on stage 0 only; replicate over the pp axis
            m0 = (idx == 0).astype(dx_acc.dtype)
            return loss, grads, lax.psum(dx_acc * m0, axis_name)
        return loss, grads

    return apply


def pipeline_value_and_grad_1f1b(stage_fn: Callable, loss_fn: Callable,
                                 stacked_params, x, targets, mesh: Mesh,
                                 n_microbatches: int, axis_name: str = "pp",
                                 batch_axis_name: Optional[str] = "dp",
                                 param_specs=None,
                                 return_input_grad: bool = False):
    """True 1F1B pipeline training step: ``(mean_loss, grads)``.

    Unlike :func:`pipeline_forward` (+ ``jax.grad``), backward work is
    interleaved per microbatch, so activation memory is bounded by the
    stage count S, not the microbatch count M — use this for deep
    microbatching (no ``M <= S`` restriction).  ``stacked_params`` has a
    leading stage axis of size mesh.shape[axis_name] (sharded over it);
    ``x``/``targets`` are (B, ...) batches split into ``n_microbatches``
    (and over ``batch_axis_name`` if present; grads/loss are averaged
    over it).  Returned grads carry the same stacked layout as
    ``stacked_params``.

    ``param_specs``: optional pytree of PartitionSpecs matching
    ``stacked_params`` for additional intra-stage sharding (e.g. tensor
    parallelism: P('pp', None, 'tp') on a column-parallel weight — the
    stage_fn is then responsible for its own 'tp' collectives).
    Defaults to P(axis_name) on every leaf.  ``return_input_grad``
    additionally returns d(mean_loss)/dx with x's sharding — already
    scaled for the dp-mean, so a caller chains it directly (e.g. into
    an embedding scatter; summing each shard's rows yields the global
    gradient).
    """
    S = mesh.shape[axis_name]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                f"1f1b: param leading (stage) axis {leaf.shape[0]} != pp "
                f"mesh size {S} — one stage per device")
    dp = (batch_axis_name
          if batch_axis_name and batch_axis_name in mesh.axis_names
          else None)
    n_dp = mesh.shape[dp] if dp else 1
    if x.shape[0] % (n_dp * n_microbatches):
        raise ValueError(
            f"1f1b: batch {x.shape[0]} not divisible by dp({n_dp}) x "
            f"n_microbatches({n_microbatches})")
    if targets.shape[0] != x.shape[0]:
        raise ValueError(
            f"1f1b: targets batch {targets.shape[0]} != x batch "
            f"{x.shape[0]} (a mismatch would silently broadcast in "
            f"loss_fn)")
    body = one_f_one_b_apply(stage_fn, loss_fn, S, n_microbatches,
                             axis_name,
                             return_input_grad=return_input_grad)

    def full(params, xb, tb):
        local = jax.tree.map(lambda a: a[0], params)   # drop sharded S
        M = n_microbatches
        xmb = xb.reshape((M, xb.shape[0] // M) + xb.shape[1:])
        tmb = tb.reshape((M, tb.shape[0] // M) + tb.shape[1:])
        res = body(local, xmb, tmb)
        loss, grads = res[0], res[1]
        if dp:
            loss = lax.pmean(loss, dp)
            grads = jax.tree.map(lambda g: lax.pmean(g, dp), grads)
        grads = jax.tree.map(lambda g: g[None], grads)
        if return_input_grad:
            dx = res[2].reshape(xb.shape)
            if dp:
                # dx rows live only on their own dp shard (a pmean
                # would mix different batch rows); the global-mean loss
                # scales each shard's contribution by 1/n_dp
                dx = (dx / n_dp).astype(dx.dtype)
            return loss, grads, dx
        return loss, grads

    if param_specs is None:
        pspec = jax.tree.map(lambda _: PartitionSpec(axis_name),
                             stacked_params)
    else:
        pspec = param_specs
    xspec = PartitionSpec(dp)
    out_specs = (PartitionSpec(), pspec) + \
        ((xspec,) if return_input_grad else ())
    return shard_map(full, mesh=mesh, in_specs=(pspec, xspec, xspec),
                     out_specs=out_specs,
                     check_vma=False)(stacked_params, x, targets)
