"""Ulysses-style sequence parallelism: all-to-all context parallelism.

The second of the two context-parallel schemes (the other is
ring_attention.py).  Each device holds a sequence shard of Q/K/V
(B, H, S/P, D).  One `lax.all_to_all` re-shards from sequence to
HEADS: afterwards every device holds the FULL sequence for H/P of the
heads and runs ordinary attention locally — no per-step ring latency —
then a second all-to-all restores sequence sharding on the output.

Trade-off vs the ring (public technique, DeepSpeed-Ulysses,
arXiv:2309.14509): communication is two all-to-alls of activations
(O(B·S·E/P) per device) instead of (P-1) K/V collective-permutes;
attention compute is a single dense local call (flash-friendly).
Prefer Ulysses when heads ≥ devices and the per-step latency of the
ring matters; prefer the ring when heads < devices or K/V are small
(GQA) so rotating them is cheaper than re-sharding activations.

The reference has no equivalent (SURVEY.md §5: long-context /
sequence parallelism absent) — this is TPU-native capability, the
all-to-alls ride ICI.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from ._shard_map_compat import shard_map

from ..ops.attention import attention_reference

__all__ = ["ulysses_attention", "ulysses_self_attention"]


def ulysses_attention(q, k, v, axis_name: str = "sp",
                      causal: bool = False,
                      sm_scale: Optional[float] = None,
                      use_flash: bool = False):
    """Per-shard Ulysses body; call inside shard_map/pjit.

    q: (B, H, S_local, D); k, v: (B, Hkv, S_local, D) — this device's
    sequence shard.  Q heads must divide by the axis size.  GQA K/V
    whose head count divides the axis ride the all-to-all SMALL
    (1/group of the traffic) and expand locally afterwards; a head
    count that doesn't divide is pre-expanded (full traffic).
    """
    p = lax.psum(1, axis_name)
    b, h, s_loc, d = q.shape
    if h % p:
        raise ValueError(
            f"ulysses: num_heads {h} not divisible by axis size {p}")
    hkv = k.shape[1]
    if hkv <= 0 or h % hkv:
        raise ValueError(f"ulysses: q heads ({h}) not divisible by kv "
                         f"heads ({hkv})")
    group = h // hkv
    if hkv % p:
        # grouped K/V don't re-shard evenly: pre-expand to full head
        # count (pays group x the K/V all-to-all traffic)
        k = jnp.repeat(k, group, axis=1)
        v = jnp.repeat(v, group, axis=1)
        group = 1
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)

    def seq_to_heads(x):
        # (B, H, S/P, D) -> (B, H/P, S, D): split the head axis across
        # the mesh, concatenate the sequence axis
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)
    if group > 1:
        # GQA with hkv % p == 0: the SMALL K/V rode the all-to-all
        # (1/group of the traffic); device i's kv heads
        # [i·hkv/p, (i+1)·hkv/p) are exactly the groups its q heads
        # [i·h/p, (i+1)·h/p) consume, so a local repeat aligns them
        kh = jnp.repeat(kh, group, axis=1)
        vh = jnp.repeat(vh, group, axis=1)
    # full local sequence for a head subset: ordinary single-device
    # attention — with use_flash the Pallas flash kernel (VMEM-blocked
    # scores + custom-vjp backward) replaces the materialized-scores
    # path for long-context memory behavior
    if use_flash:
        from ..ops.attention import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal,
                              sm_scale=scale)
    else:
        out = attention_reference(qh, kh, vh, causal=causal,
                                  sm_scale=scale)
    # (B, H/P, S, D) -> (B, H, S/P, D)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_self_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                           causal: bool = False,
                           sm_scale: Optional[float] = None,
                           use_flash: bool = False):
    """shard_map wrapper: shards the sequence axis of (B,H,S,D) over
    ``axis_name`` and runs Ulysses all-to-all attention across the
    mesh (mirror of ring_self_attention's contract)."""
    from .ring_attention import seq_shard_call

    def fn(qq, kk, vv):
        return ulysses_attention(qq, kk, vv, axis_name=axis_name,
                                 causal=causal, sm_scale=sm_scale,
                                 use_flash=use_flash)

    # pallas_call outputs (the use_flash local engine) carry no vma
    # annotation, so the checker must be off for flash; the dense path
    # keeps the shard_map vma validation it always had
    return seq_shard_call(fn, mesh, axis_name, q, k, v,
                          check_vma=not use_flash)
