"""``shard_map`` with the modern kwarg surface, on the pinned jax.

Every shard_map user in this package imports from here so the API
probe lives in one place.  Re-checked against the toolchain's jax
(0.4.x): ``jax.shard_map`` is NOT exported there — the old
``try: from jax import shard_map`` branch could never fire and has
been deleted — so this wraps ``jax.experimental.shard_map.shard_map``
directly, translating the modern ``check_vma`` kwarg to the
experimental API's ``check_rep``.  When the toolchain moves to a jax
that exports ``jax.shard_map`` (>= 0.6), this module shrinks to a
re-export.
"""
from __future__ import annotations

from jax.experimental.shard_map import shard_map as _shard_map_exp


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
    return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma,
                          **kw)


__all__ = ["shard_map"]
