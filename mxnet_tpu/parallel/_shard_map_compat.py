"""``jax.shard_map`` across jax versions.

Newer jax exports :func:`jax.shard_map` with a ``check_vma`` kwarg; older
releases only ship ``jax.experimental.shard_map.shard_map`` whose
equivalent kwarg is ``check_rep``.  Every shard_map user in this package
imports from here so the version probe lives in one place.
"""
from __future__ import annotations

try:                                     # jax >= 0.6
    from jax import shard_map            # type: ignore[attr-defined]
except ImportError:                      # jax 0.4/0.5
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kw)

__all__ = ["shard_map"]
