"""mxnet_tpu.parallel — device meshes + SPMD training.

TPU-native replacement for the reference's multi-device machinery
(SURVEY.md §2.3): instead of KVStore Comm trees / NCCL rings, a
``jax.sharding.Mesh`` over the chips and GSPMD partitioning.  Data
parallelism = shard the batch axis; tensor/sequence parallelism =
PartitionSpecs on parameters/activations; XLA inserts the all-reduces
over ICI (the reference's gpu_topology.h spanning-tree solver has no
equivalent here — the compiler owns topology).
"""
from .mesh import make_mesh, default_mesh, data_parallel_spec, replicated
from .mesh4d import MeshPlan, Mesh4DTrainer, mesh_plan_from_env
from .trainer import SPMDTrainer
from .ring_attention import (ring_attention, ring_self_attention,
                             ring_flash_attention,
                             ring_flash_self_attention)
from .ulysses import ulysses_attention, ulysses_self_attention
from .pipeline import (gpipe_apply, pipeline_forward,
                       interleaved_apply, pipeline_forward_1f1b,
                       pipeline_forward_interleaved,
                       pipeline_value_and_grad_1f1b, one_f_one_b_apply,
                       one_f_one_b_ticks,
                       interleave_params, interleaved_ticks, gpipe_ticks)
from .moe import switch_moe, moe_expert_sharding

__all__ = ["make_mesh", "default_mesh", "data_parallel_spec", "replicated",
           "MeshPlan", "Mesh4DTrainer", "mesh_plan_from_env",
           "SPMDTrainer", "ring_attention", "ring_self_attention",
           "ring_flash_attention", "ring_flash_self_attention",
           "ulysses_attention", "ulysses_self_attention",
           "gpipe_apply", "pipeline_forward", "switch_moe",
           "interleaved_apply", "pipeline_forward_1f1b",
           "pipeline_forward_interleaved", "pipeline_value_and_grad_1f1b",
           "one_f_one_b_apply", "one_f_one_b_ticks",
           "interleave_params", "interleaved_ticks", "gpipe_ticks",
           "moe_expert_sharding"]
