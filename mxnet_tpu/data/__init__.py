"""mxnet_tpu.data — device-feed input pipeline.

The host→device half of the input story: ``gluon.data`` produces host
batches (workers, batchify, shared memory); this package moves them
onto the accelerator *ahead of the step that consumes them*, so the
H2D transfer overlaps the previous step's compute instead of sitting
on the critical path (the ``PrefetcherIter`` / threaded-engine idea of
the reference, re-expressed as sharding-aware non-blocking
``jax.device_put`` — see docs/ARCHITECTURE.md "Input pipeline").
"""
from .device_pipeline import DevicePrefetcher, prefetch_depth, wrap

__all__ = ["DevicePrefetcher", "prefetch_depth", "wrap"]
