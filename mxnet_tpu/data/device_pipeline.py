"""Async device-feed pipeline: sharding-aware batch prefetch.

``DevicePrefetcher`` wraps any batch iterable — ``gluon.data.DataLoader``,
an ``io.DataIter``, a plain generator — and keeps ``MXNET_DEVICE_PREFETCH``
(default 2) batches *in flight on the device*: a background thread pulls
host batches and dispatches each leaf as a non-blocking
``jax.device_put`` against the consumer's declared
``jax.sharding.Sharding``, so SPMD batches land pre-sharded across the
``dp``/``sp`` mesh axes and the compiled step never reshards them.  The
consumer's ``next()`` then hands back an already-committed device batch:
H2D transfer (and the host-side batchify behind it) overlaps the
previous step's compute instead of serializing with it.

This is the reference's ``PrefetcherIter`` + threaded-engine dependency
tracking (src/io/iter_prefetcher.h — fetch ops scheduled on the engine
worker pool) re-expressed in JAX terms, and the standard TPU
input-pipeline shape (flax ``prefetch_to_device``): the bounded queue is
the dependency edge, the async ``device_put`` is the engine op, and the
device ring of ``depth`` staged batches is what the reference's
double-buffered prefetcher kept in its recycle queue.

Dataflow::

    workers ─▶ host queue ─▶ [H2D thread: device_put(sharding)] ─▶
        device ring (depth batches) ─▶ step funnel

Ordering is exactly the source's (single producer thread, FIFO queue),
so a wrapped loader is bitwise-deterministic against the bare loader.
``MXNET_DEVICE_PREFETCH=0`` (or ``depth=0``) disables the pipeline
entirely — ``wrap`` returns the source unchanged, reproducing the
unwrapped numerics bitwise.

Telemetry: every transferred batch accounts its payload into
``input.h2d_bytes``; every consumer ``next()`` that blocks records the
blocked time into ``input.wait_ms``.  Both surface per step as the
``h2d_bytes`` / ``input_wait_ms`` fields of the telemetry step record,
which is how ``tools/telemetry_report.py`` classifies a run as
input-bound vs compute-bound.

Window staging (``window=n_steps``): instead of one batch per item,
the producer host-stacks ``n_steps`` consecutive batches into a single
window tree whose leaves carry a leading ``n_steps`` axis, and commits
each window under the consumer's ``_window_sharding`` (step axis
replicated, batch/seq axes shifted right by one).  That is exactly the
layout ``SPMDTrainer.run_steps(..., per_step_data=True)`` declares for
its fused ``lax.scan`` window, so the whole window lands on-device once
and the scan consumes one batch per step with zero per-step H2D — the
device-side counterpart of the one-launch-per-window training loop.  A
trailing partial window (fewer than ``n_steps`` batches left in the
epoch) is dropped and counted in ``input.window_dropped``.
"""
from __future__ import annotations

import queue as _queue
import threading
import time
import weakref
from typing import Any, Callable, Iterable, Optional

import numpy as onp
import jax

from .. import telemetry
from .. import tracing
from ..base import MXNetError, getenv

__all__ = ["DevicePrefetcher", "prefetch_depth", "wrap",
           "note_advice_depth", "advised_depth"]

_DONE = "__done__"
_ERROR = "__error__"

# clustermon remediation advice (cluster.advice_* counters tell the
# story): a persistently input-bound rank is advised to deepen its
# prefetch ring.  Applied at the next epoch boundary, and ONLY when the
# pipeline is already enabled — advice never flips a depth=0 (bitwise
# passthrough) pipeline on.
_ADVICE_LOCK = threading.Lock()
_advised_depth = 0


def note_advice_depth(depth: int) -> None:
    """Record a prefetch-depth advice (monotonic max).  Called by
    ``clustermon.SpoolSink`` when an ``input_bound`` incident escalates
    and ``MXNET_REMEDIATE=1``."""
    global _advised_depth
    with _ADVICE_LOCK:
        _advised_depth = max(_advised_depth, int(depth))


def advised_depth() -> int:
    """The current advised depth (0 = no advice)."""
    return _advised_depth


def prefetch_depth(default: int = 2) -> int:
    """Batches kept in flight on-device (``MXNET_DEVICE_PREFETCH``;
    0 disables the pipeline — the bitwise-identical eager path)."""
    v = getenv("MXNET_DEVICE_PREFETCH")
    if v is None or v == "":
        return default
    try:
        return max(0, int(v))
    except ValueError:
        raise MXNetError(
            f"invalid MXNET_DEVICE_PREFETCH={v!r}; expected an integer")


def _placement_of(consumer):
    """A per-leaf placement fn from a consumer's declared sharding.

    Accepts an ``SPMDTrainer`` (its ``_batch_sharding`` per-rank
    NamedSharding — batches land pre-sharded over dp/sp), a
    ``gluon.Trainer`` (the device its parameters live on), an explicit
    ``jax.sharding.Sharding`` / ``jax.Device``, a callable
    ``leaf -> sharding``, or None (the default device)."""
    if consumer is None:
        dev = jax.devices()[0]
        return lambda leaf: dev
    if callable(consumer) and not hasattr(consumer, "_batch_sharding") \
            and not isinstance(consumer, (jax.sharding.Sharding,)):
        return consumer
    if isinstance(consumer, jax.sharding.Sharding):
        return lambda leaf: consumer
    if isinstance(consumer, jax.Device):
        return lambda leaf: consumer
    if hasattr(consumer, "_batch_sharding"):
        # parallel.SPMDTrainer: rank-dependent NamedSharding over the
        # trainer's mesh — data batch axis on 'dp', seq axis on 'sp'
        return lambda leaf: consumer._batch_sharding(leaf.ndim)
    if hasattr(consumer, "_input_placement"):
        # gluon.Trainer: single-device eager funnel — commit batches to
        # the device the parameters live on
        dev = consumer._input_placement()
        return lambda leaf: dev
    raise MXNetError(
        f"cannot derive a batch sharding from {type(consumer).__name__}; "
        "pass a jax.sharding.Sharding, a Device, a callable, or a "
        "trainer (SPMDTrainer / gluon.Trainer)")


def _place_tree(batch, place_fn):
    """Recursively dispatch every array leaf of ``batch`` to the device
    via a non-blocking ``jax.device_put`` under ``place_fn``'s sharding,
    preserving the batch structure (tuples/lists/dicts/DataBatch).
    Returns (placed batch, bytes transferred)."""
    from ..ndarray import NDArray
    nbytes = [0]

    def place(x):
        if isinstance(x, NDArray):
            arr = x._data
        elif isinstance(x, (jax.Array, onp.ndarray)):
            arr = x
        elif isinstance(x, tuple):
            return tuple(place(e) for e in x)
        elif isinstance(x, list):
            return [place(e) for e in x]
        elif isinstance(x, dict):
            return {k: place(v) for k, v in x.items()}
        else:
            # non-array payload (DataBatch.pad ints, names, None)
            return x
        target = place_fn(arr)
        if isinstance(arr, jax.Array) and getattr(arr, "_committed", False):
            shd = getattr(arr, "sharding", None)
            if shd == target or (isinstance(target, jax.Device)
                                 and shd is not None
                                 and set(arr.devices()) == {target}):
                # already committed where the consumer wants it
                return x if isinstance(x, NDArray) else NDArray(arr)
        put = jax.device_put(arr, target)   # async dispatch, no block
        nbytes[0] += int(getattr(arr, "nbytes", 0))
        return NDArray(put)

    # io.DataBatch rides as an object: rebuild with placed data/label
    if type(batch).__name__ == "DataBatch" and hasattr(batch, "data") \
            and hasattr(batch, "label"):
        from ..io.io import DataBatch
        placed = DataBatch(place(batch.data), place(batch.label),
                           pad=batch.pad, index=batch.index,
                           provide_data=batch.provide_data,
                           provide_label=batch.provide_label)
        return placed, nbytes[0]
    return place(batch), nbytes[0]


def _to_host(leaf):
    from ..ndarray import NDArray
    if isinstance(leaf, NDArray):
        leaf = leaf._data
    return onp.asarray(leaf)


def _stack_window(batches):
    """Stack ``n_steps`` structurally-identical batch trees into one
    window tree: every array leaf gains a leading ``n_steps`` axis
    (host-side ``onp.stack``); non-array payloads keep the first
    batch's value.  The stacked tree then rides through
    :func:`_place_tree` as one item, so a window pays exactly one
    ``device_put`` per leaf."""
    from ..ndarray import NDArray

    def stack(items):
        x0 = items[0]
        if isinstance(x0, tuple):
            return tuple(stack([it[i] for it in items])
                         for i in range(len(x0)))
        if isinstance(x0, list):
            return [stack([it[i] for it in items]) for i in range(len(x0))]
        if isinstance(x0, dict):
            return {k: stack([it[k] for it in items]) for k in x0}
        if isinstance(x0, (NDArray, jax.Array, onp.ndarray)):
            return onp.stack([_to_host(it) for it in items])
        return x0

    if type(batches[0]).__name__ == "DataBatch" \
            and hasattr(batches[0], "data"):
        from ..io.io import DataBatch
        return DataBatch(stack([b.data for b in batches]),
                         stack([b.label for b in batches]),
                         pad=batches[0].pad, index=batches[0].index,
                         provide_data=batches[0].provide_data,
                         provide_label=batches[0].provide_label)
    return stack(batches)


def _window_iter(src, window: int):
    """Regroup a batch iterator into whole ``window``-step windows; a
    trailing partial window is dropped (counted in
    ``input.window_dropped``) so every staged item matches the fused
    multi-step executable's fixed ``n_steps``."""
    buf = []
    try:
        for batch in src:
            buf.append(batch)
            if len(buf) == window:
                yield _stack_window(buf)
                buf = []
        if buf:
            telemetry.counter("input.window_dropped").inc(len(buf))
    finally:
        # a generator.close() on this iterator (pipeline shutdown) must
        # reach the wrapped source's own teardown (DataLoader shm drain)
        close = getattr(src, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                pass


def _shutdown(stop, q, thread, src_it):
    """Tear one epoch pipeline down: no live thread, no in-flight
    device_put, and the source generator's own cleanup (the DataLoader
    shm drain) has run.  Runs from close(), from the weakref finalizer
    when an interrupted consumer drops the iterator, and at natural
    exhaustion."""
    stop.set()
    # drain so a producer blocked on a full queue can observe stop
    while True:
        try:
            q.get_nowait()
        except _queue.Empty:
            break
    if thread is not None and thread.is_alive() \
            and thread is not threading.current_thread():
        thread.join(timeout=10)
    # after the producer has exited, run the source's own teardown —
    # for a DataLoader generator this is the finally-drain that unlinks
    # disowned shm segments
    close = getattr(src_it, "close", None)
    if close is not None:
        try:
            close()
        except Exception:
            pass


def _produce(src, q, stop, place_fn, skip=0):
    """Producer loop (module-level: the thread must hold no reference
    to the pipeline object, so an abandoned pipeline can be collected
    and its finalizer can stop this thread).

    ``skip``: batches to draw from the source and DROP before staging
    any — deterministic-resume replay (the source's sampler/RNG state
    advances exactly as in the original run) without paying H2D for
    batches the resumed run will not train on."""
    def put(item) -> bool:
        # bounded put that stays responsive to shutdown: never blocks
        # forever on a ring the consumer abandoned
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except _queue.Full:
                continue
        return False

    if tracing.enabled():
        tracing.register_thread()
    try:
        for _ in range(skip):
            if stop.is_set():
                return
            try:
                next(src)               # replay, no device staging
            except StopIteration:
                put((_DONE, None))
                return
        if skip:
            telemetry.counter("input.replayed").inc(skip)
        while not stop.is_set():
            with tracing.span("input.produce") as sp:
                try:
                    batch = next(src)
                except StopIteration:
                    put((_DONE, None))
                    return
                with tracing.span("input.h2d") as h2d:
                    placed, nbytes = _place_tree(batch, place_fn)
                    h2d.annotate(h2d_nbytes=nbytes)
                sp.annotate(h2d_nbytes=nbytes)
            if nbytes:
                telemetry.record_h2d_bytes(nbytes)
            if not put((None, placed)):
                return
    except BaseException as e:   # surface at the consumer's next()
        put((_ERROR, e))


class _EpochPipeline:
    """One epoch's producer thread + bounded device ring.  Created per
    ``__iter__`` so a prefetcher can be re-iterated epoch after epoch."""

    def __init__(self, src_it, place_fn, depth: int, name: str,
                 skip: int = 0):
        self._q: _queue.Queue = _queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=_produce,
            args=(src_it, self._q, self._stop, place_fn, skip),
            name=f"DevicePrefetch-{name}", daemon=True)
        # interrupted consumer (break mid-epoch): the for-loop drops its
        # reference and the finalizer stops the thread, drains the ring
        # and closes the source — no explicit close() required
        self._finalizer = weakref.finalize(
            self, _shutdown, self._stop, self._q, self._thread, src_it)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        t0 = time.perf_counter()
        item = self._q.get()
        t1 = time.perf_counter()
        telemetry.record_input_wait(t1 - t0)
        tracing.record_span("input.wait", t0, t1)
        tag, payload = item
        if tag is None:
            return payload
        self.close()
        if tag == _ERROR:
            raise payload
        raise StopIteration

    def close(self):
        self._finalizer()


class DevicePrefetcher:
    """Wrap a batch iterable so batches arrive device-committed, with
    ``depth`` batches staged on-device ahead of the consumer.

    Parameters
    ----------
    source : iterable
        Any batch source: ``gluon.data.DataLoader``, ``io.DataIter``,
        generator, list.  Re-iterables re-iterate (one epoch per
        ``__iter__``); one-shot iterators are consumed once.
    sharding : optional
        Where batches land: a ``jax.sharding.Sharding``, a
        ``jax.Device``, a callable ``leaf -> sharding``, a trainer
        (``SPMDTrainer`` / ``gluon.Trainer``), or None for the default
        device.  See :func:`wrap` for the trainer-driven spelling.
    depth : int, optional
        Batches kept in flight on-device; default
        ``MXNET_DEVICE_PREFETCH`` (2).  0 disables: iteration passes the
        source through untouched (bitwise-identical eager path).
    window : int, optional
        Stage whole ``window``-step windows instead of single batches:
        each item is ``window`` consecutive source batches host-stacked
        along a new leading step axis and committed under the
        consumer's ``_window_sharding`` (when it declares one) — the
        input layout of ``SPMDTrainer.run_steps(per_step_data=True)``.
        A trailing partial window is dropped (``input.window_dropped``).
        Windowing applies even at ``depth=0`` (host-stacked, staged
        inline by the consumer).
    """

    def __init__(self, source: Iterable, sharding: Any = None,
                 depth: Optional[int] = None, name: Optional[str] = None,
                 window: Optional[int] = None):
        self._source = source
        self._window = 1 if window is None else max(1, int(window))
        if self._window > 1 and hasattr(sharding, "_window_sharding"):
            # SPMDTrainer window layout: leading n_steps axis replicated,
            # batch/seq mesh axes shifted right by one
            self._place_fn = lambda leaf: sharding._window_sharding(leaf.ndim)
        else:
            self._place_fn = _placement_of(sharding)
        self._depth = prefetch_depth() if depth is None else max(0, int(depth))
        self._name = name or type(source).__name__
        self._live: Optional[_EpochPipeline] = None
        self._plain = None
        self._skip_next = 0

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def window(self) -> int:
        return self._window

    def __len__(self):
        n = len(self._source)
        return n // self._window if self._window > 1 else n

    def fast_forward(self, n: int) -> None:
        """Arrange for the NEXT epoch (``__iter__``) to draw and DROP
        its first ``n`` items before staging any on-device — the
        deterministic-resume replay used by checkpointed training
        loops (``SPMDTrainer.fit``): the source's sampler/shuffle state
        advances exactly as in the interrupted run, but the skipped
        items pay no H2D transfer.  With ``window > 1`` an item is a
        whole window, so ``n`` counts WINDOWS (= resumed ``run_steps``
        calls), not individual batches."""
        self._skip_next = max(0, int(n))

    def _source_iter(self):
        it = iter(self._source)
        return _window_iter(it, self._window) if self._window > 1 else it

    def __iter__(self):
        skip, self._skip_next = self._skip_next, 0
        if self._depth <= 0:
            it = self._source_iter()
            for _ in range(skip):
                try:
                    next(it)            # replay, passthrough path
                except StopIteration:
                    break
            return it
        # remediation advice deepens an ENABLED pipeline at the epoch
        # boundary; a depth=0 passthrough stays bitwise untouched above
        depth = max(self._depth, _advised_depth)
        self.close()   # a fresh epoch retires any abandoned pipeline
        self._live = _EpochPipeline(self._source_iter(), self._place_fn,
                                    depth, self._name, skip=skip)
        return self._live

    # -- io.DataIter protocol parity ------------------------------------
    def __next__(self):
        if self._depth <= 0:
            if self._plain is None:
                self._plain = self.__iter__()
            try:
                return next(self._plain)
            except StopIteration:
                self._plain = None
                raise
        if self._live is None:
            self.__iter__()
        return next(self._live)

    def next(self):
        return self.__next__()

    def reset(self):
        """DataIter parity: tear down the in-flight epoch and reset the
        source so the next iteration starts fresh."""
        self.close()
        self._plain = None
        reset = getattr(self._source, "reset", None)
        if reset is not None:
            reset()

    def close(self):
        """Stop the producer thread and drop the staged device ring."""
        if self._live is not None:
            self._live.close()
            self._live = None


def wrap(source: Iterable, consumer: Any = None,
         depth: Optional[int] = None, window: Optional[int] = None):
    """Wrap ``source`` in a :class:`DevicePrefetcher` targeting
    ``consumer``'s declared batch sharding.

    ``consumer`` may be a ``parallel.SPMDTrainer`` (batches land
    pre-sharded over the trainer's dp/sp mesh axes, so the compiled step
    performs no ``device_put``), a ``gluon.Trainer`` (batches commit to
    the parameters' device), an explicit sharding/device/callable, or
    None (default device).  With ``MXNET_DEVICE_PREFETCH=0`` (or
    ``depth=0``) the source is returned **unchanged** — the untouched
    eager path, bitwise identical.

    ``window=n_steps`` stages whole multi-step windows pre-sharded for
    ``SPMDTrainer.run_steps(..., per_step_data=True)`` — see
    :class:`DevicePrefetcher`.  Windowing is structural (the consumer
    expects ``(n_steps, batch, ...)`` leaves), so it applies even when
    prefetch is disabled: at ``depth=0`` the wrapper still regroups the
    source into host-stacked windows, it just stages nothing on-device.
    """
    d = prefetch_depth() if depth is None else max(0, int(depth))
    w = 1 if window is None else max(1, int(window))
    if d <= 0 and w <= 1:
        return source
    return DevicePrefetcher(source, sharding=consumer, depth=d, window=w)
