"""Symbol attribute scopes.

Parity: python/mxnet/attribute.py — ``AttrScope``: a thread-local stack
of attribute dicts applied to every symbol created inside the scope
(`with mx.AttrScope(ctx_group='stage1'):` in the reference's manual
model-parallel idiom).  Symbols store the merged attrs in
``Symbol.attr``/``list_attr``.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current"]

_state = threading.local()


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [AttrScope()]
    return _state.stack


def current() -> "AttrScope":
    return _stack()[-1]


class AttrScope:
    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError("AttrScope values must be strings")
        self._attr = dict(kwargs)

    def get(self, attr: Optional[Dict[str, str]]) -> Dict[str, str]:
        """Merge scope attrs with per-symbol attrs (symbol wins)."""
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        merged = AttrScope()
        merged._attr = current().get(self._attr)
        _stack().append(merged)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False
