"""Generate Python op functions from the registry at import time.

Parity: the reference code-gens ``mx.nd.*`` op modules from the C
registry on import (python/mxnet/ndarray/register.py:115-277,
``_init_op_module`` base.py:601).  Here the registry is Python, so
"codegen" is building wrapper functions that split positional NDArray
inputs from scalar/static params using the op function's signature.
"""
from __future__ import annotations

import inspect
from typing import Any, Dict

from ..ops import registry as _reg
from ..ops.registry import apply_jax

__all__ = ["make_op_func", "populate_namespace"]


def _analyze(fn):
    sig = inspect.signature(fn)
    arr_params = []     # positional (array) parameter names
    kw_params = []      # keyword-only (static attr) names
    has_var_pos = False
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            arr_params.append(p.name)
        elif p.kind == p.VAR_POSITIONAL:
            has_var_pos = True
        elif p.kind == p.KEYWORD_ONLY:
            kw_params.append(p.name)
    return arr_params, kw_params, has_var_pos


def make_op_func(name: str):
    """Build the user-facing function for a registered op."""
    op = _reg.get(name)
    arr_params, kw_params, var_pos = _analyze(op.fn)
    n_arr = len(arr_params)

    def op_func(*args, out=None, name=None, **kwargs):
        from .ndarray import NDArray

        if var_pos:
            inputs = [a for a in args if isinstance(a, NDArray)]
        else:
            inputs, extra = [], []
            for i, a in enumerate(args):
                if isinstance(a, NDArray):
                    inputs.append(a)
                elif a is None and i < n_arr:
                    continue  # optional array input omitted
                else:
                    # scalar positional → map onto keyword-only params in order
                    extra.append(a)
            for pname, val in zip(
                    [k for k in kw_params if k not in kwargs], extra):
                kwargs[pname] = val
        # normalize list params to tuples (hashable, jit-safe)
        for k, v in list(kwargs.items()):
            if isinstance(v, list):
                kwargs[k] = tuple(v)
        result = _reg.dispatch(op, inputs, kwargs)
        if out is not None:
            outs = result if isinstance(result, list) else [result]
            targets = out if isinstance(out, (list, tuple)) else [out]
            for t, r in zip(targets, outs):
                t._adopt(r)
            return out
        return result

    op_func.__name__ = name
    op_func.__doc__ = op.doc or f"Registered op {name} (see mxnet_tpu.ops)."
    return op_func


def populate_namespace(ns: Dict[str, Any], names=None) -> None:
    """Install op functions into a module namespace dict."""
    for name in (names or _reg.list_ops()):
        if name.startswith("_random") or name.startswith("_sample"):
            continue  # exposed via .random with key plumbing
        if name not in ns:
            ns[name] = make_op_func(name)
