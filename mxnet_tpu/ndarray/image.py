"""mx.nd.image — image op namespace.

Parity: python/mxnet/ndarray/image.py (generated `_image_*` bindings
exposed under short names: mx.nd.image.to_tensor/normalize/crop/
resize/random_crop/random_resized_crop over src/operator/image/).
The random variants draw entropy from the global key chain like every
other random op.
"""
from __future__ import annotations

import functools

from ..ops import registry as _reg
from ..ops.random import next_key
from ..ops.registry import apply_jax
from .register import make_op_func

__all__ = ["to_tensor", "normalize", "crop", "resize", "random_crop",
           "random_resized_crop"]

to_tensor = make_op_func("_image_to_tensor")
normalize = make_op_func("_image_normalize")
crop = make_op_func("_image_crop")
resize = make_op_func("_image_resize")


def _random_image_op(op_name, img, **params):
    """Key-drawing image op: record=False keeps the fresh PRNG key out
    of autograd tapes / deferred-compute graphs (same convention as
    ndarray/random.py shuffle/multinomial — a recorded key would
    replay the identical 'random' transform on export)."""
    from .ndarray import NDArray

    fn = functools.partial(_reg.get(op_name).fn, **params)
    return apply_jax(lambda k, d: fn(k, d),
                     [NDArray(next_key()), img], record=False)


def random_crop(img, size, **kwargs):
    return _random_image_op("_image_random_crop", img, size=size,
                            **kwargs)


def random_resized_crop(img, size, **kwargs):
    return _random_image_op("_image_random_resized_crop", img,
                            size=size, **kwargs)
