"""Control-flow and contrib NDArray ops.

Parity: ``mx.nd.contrib.foreach / while_loop / cond``
(src/operator/control_flow.cc:1094,1155,1216 — subgraph-executing
stateful ops with full backward; python/mxnet/ndarray/contrib.py).
TPU-native: the user body is traced into ``lax.scan`` /
``lax.while_loop`` / ``lax.cond`` — compiler-friendly control flow
instead of subgraph re-execution, differentiable because the whole
construct is recorded on the autograd tape as one op.

Closed-over NDArrays (e.g. RNN weights referenced inside the body) are
discovered with a one-shot capture trace (`CaptureScope`) and threaded
as real inputs, so gradients flow to them — the analogue of the
reference's control-flow subgraph input capture.

``while_loop`` follows the reference contract that ``max_iterations``
bounds the loop; it lowers to a bounded, predicate-gated ``lax.scan``
so it stays reverse-differentiable (jax's ``while_loop`` is not), and
trims outputs to the realized step count outside of traces.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .. import autograd as ag
from ..ops.registry import apply_jax, CaptureScope
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond", "isfinite", "isnan", "isinf",
           "boolean_mask"]


def _as_list(x) -> Tuple[List[Any], bool]:
    if isinstance(x, (list, tuple)):
        return list(x), False
    return [x], True


def _wrap(arrays) -> List[NDArray]:
    return [NDArray(a) for a in arrays]


def _raw(nds) -> List[Any]:
    out = []
    for x in (nds if isinstance(nds, (list, tuple)) else [nds]):
        out.append(x._data if isinstance(x, NDArray) else jnp.asarray(x))
    return out


def _nd(x) -> NDArray:
    return x if isinstance(x, NDArray) else NDArray(x)


class _swapped:
    """Temporarily rebind captured NDArrays' buffers to traced values."""

    def __init__(self, nds, arrays):
        self._nds = list(nds)
        self._arrays = list(arrays)

    def __enter__(self):
        self._saved = [p._data for p in self._nds]
        for p, a in zip(self._nds, self._arrays):
            p._data = a
        return self

    def __exit__(self, *exc):
        for p, s in zip(self._nds, self._saved):
            p._data = s
        return False


def foreach(body: Callable, data, init_states, name: str = "foreach"):
    """Iterate ``body(data_t, states) -> (outputs, new_states)`` over
    axis 0 of ``data`` (parity: control_flow.cc `_foreach`)."""
    data_list, data_single = _as_list(data)
    states_list, states_single = _as_list(init_states)
    data_list = [_nd(x) for x in data_list]
    states_list = [_nd(x) for x in states_list]
    n_data, n_states = len(data_list), len(states_list)

    with CaptureScope() as scope, ag.pause():
        d0 = [x[0] for x in data_list]
        body(d0[0] if data_single else d0,
             states_list[0] if states_single else list(states_list))
    captured = scope.captured(exclude=data_list + states_list)

    def fn(*arrays):
        xs = tuple(arrays[:n_data])
        init = tuple(arrays[n_data:n_data + n_states])
        cap = arrays[n_data + n_states:]

        def step(carry, x):
            with _swapped(captured, cap), ag.pause():
                x_nd = _wrap(x)
                c_nd = _wrap(carry)
                out, new_states = body(
                    x_nd[0] if data_single else x_nd,
                    c_nd[0] if states_single else c_nd)
            return tuple(_raw(new_states)), tuple(_raw(out))

        carry, ys = lax.scan(step, init, xs)
        return tuple(ys) + tuple(carry)

    flat = apply_jax(fn, data_list + states_list + captured, multi_out=True)
    outs, states = flat[:len(flat) - n_states], flat[len(flat) - n_states:]
    return (outs[0] if len(outs) == 1 else list(outs),
            states[0] if states_single else list(states))


def while_loop(cond: Callable, func: Callable, loop_vars,
               max_iterations: int | None = None, name: str = "while_loop"):
    """Bounded while loop (parity: control_flow.cc `_while_loop`).

    ``cond(*loop_vars) -> boolean scalar``; ``func(*loop_vars) ->
    (step_output, new_loop_vars)``.  Returns (stacked outputs, final
    loop vars); outputs beyond the realized iteration count are
    dropped eagerly (zero-padded under jit, as shapes must be static).
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    lv_list, lv_single = _as_list(loop_vars)
    lv_list = [_nd(x) for x in lv_list]
    n_vars = len(lv_list)

    with CaptureScope() as scope, ag.pause():
        cond(*lv_list)
        func(*lv_list)
    captured = scope.captured(exclude=lv_list)

    def fn(*arrays):
        init = tuple(arrays[:n_vars])
        cap = arrays[n_vars:]

        def run_body(vals):
            with _swapped(captured, cap), ag.pause():
                v_nd = _wrap(vals)
                out, new_vars = func(*v_nd)
                out_l, _ = _as_list(out)
                new_l, _ = _as_list(new_vars)
                pred = cond(*_wrap(_raw(new_l)))
            return (tuple(_raw(new_l)), tuple(_raw(out_l)),
                    jnp.asarray(_raw([pred])[0], bool).reshape(()))

        def step(carry, _):
            vals, active, count = carry

            def run(args):
                vals, count = args
                new_vals, outs, still = run_body(vals)
                return new_vals, outs, still, count + 1

            def skip(args):
                vals, count = args
                _, outs, _ = run_body(vals)
                zeros = tuple(jnp.zeros_like(o) for o in outs)
                return vals, zeros, jnp.asarray(False), count

            new_vals, outs, still, count = lax.cond(
                active, run, skip, (vals, count))
            return (new_vals, active & still, count), outs

        with _swapped(captured, cap), ag.pause():
            pred0 = cond(*_wrap(init))
        (vals, _, count), ys = lax.scan(
            step, (init, jnp.asarray(_raw([pred0])[0], bool).reshape(()),
                   jnp.asarray(0, jnp.int32)),
            None, length=max_iterations)
        return tuple(ys) + tuple(vals) + (count,)

    flat = apply_jax(fn, lv_list + captured, multi_out=True)
    count = flat[-1]
    outs = flat[:len(flat) - n_vars - 1]
    final_vars = flat[len(flat) - n_vars - 1:-1]
    try:  # eager: trim to realized steps (parity: dynamic-length outputs)
        n = int(count.asnumpy())
        outs = [o[:n] for o in outs]
    except (jax.errors.TracerArrayConversionError,
            jax.errors.ConcretizationTypeError):
        pass  # inside a trace: shapes stay static, padded with zeros
    return (outs[0] if len(outs) == 1 else list(outs),
            final_vars[0] if lv_single else list(final_vars))


def cond(pred, then_func: Callable, else_func: Callable, name: str = "cond"):
    """Conditional execution (parity: control_flow.cc `_cond`).

    ``pred`` is a scalar NDArray/boolean; branches are zero-arg
    callables returning NDArrays with matching shapes."""
    pred_nd = _nd(pred)

    with CaptureScope() as scope, ag.pause():
        then_func()
        else_func()
    captured = scope.captured(exclude=[pred_nd])

    def fn(p, *cap):
        def then_branch(_):
            with _swapped(captured, cap), ag.pause():
                out, _ = _as_list(then_func())
            return tuple(_raw(out))

        def else_branch(_):
            with _swapped(captured, cap), ag.pause():
                out, _ = _as_list(else_func())
            return tuple(_raw(out))

        return lax.cond(jnp.asarray(p, bool).reshape(()),
                        then_branch, else_branch, operand=None)

    flat = apply_jax(fn, [pred_nd] + captured, multi_out=True)
    return flat[0] if len(flat) == 1 else flat


# -- small contrib helpers (parity: mx.contrib misc ops) -------------------

def isfinite(data):
    return apply_jax(lambda x: jnp.isfinite(x).astype(jnp.float32), [data])


def isnan(data):
    return apply_jax(lambda x: jnp.isnan(x).astype(jnp.float32), [data])


def isinf(data):
    return apply_jax(lambda x: jnp.isinf(x).astype(jnp.float32), [data])


# -- registry-backed contrib ops ------------------------------------------
# Every op registered as ``_contrib_<Name>`` surfaces here as
# ``mx.nd.contrib.<Name>`` — the analogue of the reference's codegen of
# the contrib namespace (python/mxnet/ndarray/register.py).

def boolean_mask(data, index, axis: int = 0):
    """Select rows of ``data`` where ``index`` is nonzero (parity:
    src/operator/contrib/boolean_mask.cc, with backward).

    The mask is read eagerly (dynamic output shape, like the reference's
    FComputeEx dense op); the recorded computation is a static gather,
    so gradients flow to ``data`` (scatter-add via the gather VJP).
    """
    import numpy as _onp
    import jax.numpy as _jnp
    from ..ops.registry import apply_jax as _apply

    data = _nd(data)
    idx = _onp.asarray(_nd(index).asnumpy()).astype(bool)
    sel = _jnp.asarray(_onp.nonzero(idx)[0], _jnp.int32)
    ax = axis
    return _apply(lambda d: _jnp.take(d, sel, axis=ax), [data])


from .dgl import (dgl_csr_neighbor_uniform_sample,          # noqa: E402
                  dgl_csr_neighbor_non_uniform_sample, dgl_subgraph,
                  dgl_adjacency, dgl_graph_compact)

__all__ += ["dgl_csr_neighbor_uniform_sample",
            "dgl_csr_neighbor_non_uniform_sample", "dgl_subgraph",
            "dgl_adjacency", "dgl_graph_compact"]


def _populate_contrib():
    from ..ops import registry as _reg
    from .register import make_op_func
    for _n in _reg.list_ops():
        if _n.startswith("_contrib_"):
            short = _n[len("_contrib_"):]
            if short not in globals():
                globals()[short] = make_op_func(_n)
                __all__.append(short)


_populate_contrib()
