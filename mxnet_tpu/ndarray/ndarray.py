"""NDArray: the imperative tensor.

TPU-native re-expression of the reference NDArray
(``include/mxnet/ndarray.h:82``, ``src/ndarray/ndarray.cc``): a handle
wrapping an XLA device buffer (``jax.Array``) whose async-dispatch
semantics replace the dependency-engine variable protocol —
``wait_to_read`` == ``block_until_ready``.  In-place mutation rebinds the
underlying immutable buffer and bumps the autograd version node (the
engine-var version counter survives as node identity).
"""
from __future__ import annotations

import numbers
from typing import Any, Optional, Sequence

import numpy as onp
import jax
import jax.numpy as jnp

from ..base import MXNetError, np_dtype, dtype_name, check_shape
from ..context import Context, current_context
from .. import autograd as ag
from .. import telemetry as _telemetry
from ..imperative import cached_step as _cs
from ..ops import registry as _reg
from ..ops.registry import apply_jax, invoke

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "linspace", "eye", "concat", "stack", "waitall", "save", "load",
           "load_frombuffer", "from_numpy", "from_dlpack",
           "to_dlpack_for_read", "to_dlpack_for_write"]


def _as_jax(data, ctx: Optional[Context], dtype) -> jax.Array:
    if isinstance(data, NDArray):
        data = data._data
    data = _cs.resolve(data)   # graph break: constructing from deferred
    if isinstance(data, jax.Array):
        arr = data if dtype is None else data.astype(np_dtype(dtype))
        if ctx is not None:
            arr = jax.device_put(arr, ctx.jax_device)
        return arr
    was_numpy = isinstance(data, onp.ndarray)
    np_arr = onp.asarray(data, dtype=np_dtype(dtype) if dtype is not None else None)
    if dtype is None:
        if not was_numpy:
            # python lists/scalars default to float32 (MXNet default dtype)
            np_arr = np_arr.astype(onp.float32)
        elif np_arr.dtype == onp.float64 and not jax.config.jax_enable_x64:
            # without the x64/large-tensor switch jax would truncate f64
            # anyway (with a warning); do it explicitly.  With the switch
            # on (util.set_large_tensor) f64 is preserved, like the
            # reference keeps numpy float64 input as float64.
            np_arr = np_arr.astype(onp.float32)
    dev = (ctx or current_context()).jax_device
    # host numpy → device buffer: the H2D payload accounting every
    # eager-funnel input transfer flows through (telemetry h2d_bytes;
    # prefetched batches skip this branch — they arrive as committed
    # jax.Arrays above)
    _telemetry.record_h2d_bytes(np_arr.nbytes)
    return jax.device_put(jnp.asarray(np_arr), dev)


# traced-scalar twins of the *_scalar ops for operator sugar: the
# scalar rides as a device argument (one compiled executable serves
# every value) instead of a static param (which would compile per
# value).  One Operator instance per name → stable fn identity, so the
# dispatch funnel's forward/backward caches and the profiler all engage.
_SUGAR_OPS: dict = {}


def _scalar_sugar_op(sname: str):
    op = _SUGAR_OPS.get(sname)
    if op is None:
        from ..ops.legacy import scalar_ufunc
        f, rev, logic = scalar_ufunc(sname)

        def fn(x, s, _f=f, _rev=rev, _logic=logic):
            out = _f(s, x) if _rev else _f(x, s)
            return out.astype(x.dtype) if _logic else out

        fn.__name__ = sname
        op = _SUGAR_OPS[sname] = _reg.Operator(sname, fn)
    return op


class NDArray:
    """Multi-dimensional array on a device, with autograd hooks.

    Parity: mx.nd.NDArray (python/mxnet/ndarray/ndarray.py).
    """

    __slots__ = ("_data", "_node", "_grad", "_dc_sym", "__weakref__")

    def __init__(self, data, ctx: Optional[Context] = None, dtype=None):
        self._data = _as_jax(data, ctx, dtype)
        self._node = None
        self._grad = None

    # -- autograd plumbing (used by mxnet_tpu.autograd) --------------------
    def _ensure_node(self):
        if self._node is None:
            self._node = ag._Node()
        return self._node

    def _new_node(self):
        self._node = ag._Node()
        return self._node

    def _adopt(self, other: "NDArray"):
        """In-place update: take other's buffer + graph node, keep grad attach."""
        old = self._node
        self._data = other._data
        self._node = other._node
        if old is not None and old.grad_array is not None:
            node = self._ensure_node()
            node.grad_array = old.grad_array
            node.grad_req = old.grad_req
        return self

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(self._data.dtype)

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        _cs.ensure_real(self)
        dev = next(iter(self._data.devices()))
        return Context("cpu" if dev.platform == "cpu" else "tpu", dev.id)

    ctx = context
    device = context

    @property
    def T(self):
        return self.transpose()

    @property
    def stype(self):
        return "default"  # sparse storage types: see sparse module

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate gradient buffer and mark for autograd
        (parity: ndarray.py attach_grad)."""
        self._grad = NDArray(jnp.zeros(self.shape, self.dtype))
        ag.mark_variables([self], [self._grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        ag.backward([self], [out_grad] if out_grad is not None else None,
                    retain_graph=retain_graph, train_mode=train_mode)

    def detach(self) -> "NDArray":
        out = NDArray(self._data)
        return out

    # -- sync / transfer (parity: WaitToRead, CopyFromTo, asnumpy) ---------
    # every host-sync point resolves a deferred buffer first: reading a
    # value inside a captured step is a graph break (the pending step
    # materializes eagerly — see imperative/cached_step.py)
    def wait_to_read(self):
        _cs.ensure_real(self)
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    # DLPack protocol: delegate to the backing jax.Array so
    # torch.from_dlpack(nd) / np.from_dlpack(nd) work directly
    def __dlpack__(self, *args, **kwargs):
        _cs.ensure_real(self)
        return self._data.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        _cs.ensure_real(self)
        return self._data.__dlpack_device__()

    def asnumpy(self) -> onp.ndarray:
        _cs.ensure_real(self)
        return onp.asarray(jax.device_get(self._data))

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(()).item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size != 1:
            raise MXNetError("truth value of multi-element NDArray is ambiguous")
        return bool(self.asscalar())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True) -> "NDArray":
        if not copy and self.dtype == np_dtype(dtype):
            return self
        return invoke("cast", [self], dtype=dtype_name(np_dtype(dtype)))

    def copy(self) -> "NDArray":
        return NDArray(self._data)

    def copyto(self, other):
        _cs.ensure_real(self)
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device))
        if isinstance(other, NDArray):
            _cs.ensure_real(other)
            other._rebind(jax.device_put(
                self._data.astype(other.dtype),
                next(iter(other._data.devices()))))
            return other
        raise TypeError(f"copyto: unsupported target {type(other)}")

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device))

    as_in_ctx = as_in_context
    to_device = as_in_context

    def as_nd_ndarray(self):
        return self

    def as_np_ndarray(self):
        from ..numpy import ndarray as np_ndarray
        out = np_ndarray(self._data)
        out._node = self._node
        return out

    # -- mutation ----------------------------------------------------------
    def _rebind(self, new_data: jax.Array):
        """Replace buffer contents; bumps the autograd version
        (parity: engine var version increment on write)."""
        new_data = _cs.resolve(new_data)   # writing deferred data breaks
        old = self._node
        self._data = new_data
        self._node = None
        if old is not None and old.grad_array is not None:
            node = self._ensure_node()
            node.grad_array = old.grad_array
            node.grad_req = old.grad_req
        return self

    def __setitem__(self, key, value):
        _cs.ensure_real(self)
        key = _norm_index(key, self.shape)
        if isinstance(value, NDArray):
            if not ag.is_recording():
                _cs.ensure_real(value)
            if ag.is_recording():
                res = apply_jax(lambda d, v: d.at[key].set(v.astype(d.dtype)),
                                [self, value])
                self._adopt(res)
                return
            self._rebind(self._data.at[key].set(value._data.astype(self.dtype)))
        else:
            val = jnp.asarray(value, dtype=self.dtype) if not isinstance(
                value, jax.Array) else value
            self._rebind(self._data.at[key].set(val))

    def __getitem__(self, key):
        if isinstance(key, NDArray):
            idx = key._data.astype(jnp.int32)
            return apply_jax(lambda d: jnp.take(d, idx, axis=0), [self])
        key = _norm_index(key, self.shape)
        return apply_jax(lambda d: d[key], [self])

    # -- arithmetic --------------------------------------------------------
    # scalar sugar routes through the registered *_scalar ops so it hits
    # the same dispatch funnel as named ops (profiler hook + compiled-
    # executable cache), exactly like the reference's scalar op rewrite
    # (python/mxnet/ndarray/ndarray.py _ufunc_helper)
    _SCALAR_OPS = {
        ("elemwise_add", False): "_plus_scalar",
        ("elemwise_add", True): "_plus_scalar",
        ("elemwise_sub", False): "_minus_scalar",
        ("elemwise_sub", True): "_rminus_scalar",
        ("elemwise_mul", False): "_mul_scalar",
        ("elemwise_mul", True): "_mul_scalar",
        ("elemwise_div", False): "_div_scalar",
        ("elemwise_div", True): "_rdiv_scalar",
        ("broadcast_mod", False): "_mod_scalar",
        ("broadcast_mod", True): "_rmod_scalar",
        ("broadcast_power", False): "_power_scalar",
        ("broadcast_power", True): "_rpower_scalar",
        ("broadcast_equal", False): "_equal_scalar",
        ("broadcast_not_equal", False): "_not_equal_scalar",
        ("broadcast_greater", False): "_greater_scalar",
        ("broadcast_greater_equal", False): "_greater_equal_scalar",
        ("broadcast_lesser", False): "_lesser_scalar",
        ("broadcast_lesser_equal", False): "_lesser_equal_scalar",
    }

    def _binop(self, other, name, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return invoke(name, [a, b])
        if isinstance(other, (numbers.Number, onp.number)):
            c = other
            if not isinstance(c, bool):
                sname = self._SCALAR_OPS.get((name, bool(reverse)))
                if sname is not None:
                    op = _scalar_sugar_op(sname)
                    s = NDArray(jnp.asarray(c, self._data.dtype))
                    return _reg.dispatch(op, [self, s], {})
            op = _reg.get(name).fn
            if reverse:
                return apply_jax(lambda x: op(jnp.asarray(c, x.dtype)
                                              if not isinstance(c, bool) else c, x),
                                 [self])
            return apply_jax(lambda x: op(x, jnp.asarray(c, x.dtype)
                                          if not isinstance(c, bool) else c), [self])
        return NotImplemented

    def __add__(self, o): return self._binop(o, "elemwise_add")
    def __radd__(self, o): return self._binop(o, "elemwise_add", True)
    def __sub__(self, o): return self._binop(o, "elemwise_sub")
    def __rsub__(self, o): return self._binop(o, "elemwise_sub", True)
    def __mul__(self, o): return self._binop(o, "elemwise_mul")
    def __rmul__(self, o): return self._binop(o, "elemwise_mul", True)
    def __truediv__(self, o): return self._binop(o, "elemwise_div")
    def __rtruediv__(self, o): return self._binop(o, "elemwise_div", True)
    def __mod__(self, o): return self._binop(o, "broadcast_mod")
    def __rmod__(self, o): return self._binop(o, "broadcast_mod", True)
    def __pow__(self, o): return self._binop(o, "broadcast_power")
    def __rpow__(self, o): return self._binop(o, "broadcast_power", True)
    def __matmul__(self, o): return self._binop(o, "matmul")

    def __floordiv__(self, o):
        if isinstance(o, NDArray):
            return apply_jax(lambda a, b: jnp.floor_divide(a, b), [self, o])
        return apply_jax(lambda a: jnp.floor_divide(a, o), [self])

    def __iadd__(self, o): return self._adopt(self.__add__(o))
    def __isub__(self, o): return self._adopt(self.__sub__(o))
    def __imul__(self, o): return self._adopt(self.__mul__(o))
    def __itruediv__(self, o): return self._adopt(self.__truediv__(o))

    def __neg__(self): return invoke("negative", [self])
    def __abs__(self): return invoke("abs", [self])

    def __eq__(self, o): return self._binop(o, "broadcast_equal")
    def __ne__(self, o): return self._binop(o, "broadcast_not_equal")
    def __gt__(self, o): return self._binop(o, "broadcast_greater")
    def __ge__(self, o): return self._binop(o, "broadcast_greater_equal")
    def __lt__(self, o): return self._binop(o, "broadcast_lesser")
    def __le__(self, o): return self._binop(o, "broadcast_lesser_equal")

    __hash__ = None  # mutable

    def __repr__(self):
        return f"\n{self.asnumpy()!r}\n<NDArray {('x'.join(map(str, self.shape)))} " \
               f"@{self.context}>"

    # -- method-style ops --------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if "shape" in kwargs:
            shape = kwargs["shape"]
        elif len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return invoke("reshape", [self], shape=tuple(shape),
                      reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return invoke("reshape", [self], shape=other.shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return invoke("transpose", [self], axes=axes or None)

    def flatten(self): return invoke("flatten", [self])
    def expand_dims(self, axis): return invoke("expand_dims", [self], axis=axis)
    def squeeze(self, axis=None): return invoke("squeeze", [self], axis=axis)
    def swapaxes(self, dim1, dim2):
        return invoke("swapaxes", [self], dim1=dim1, dim2=dim2)

    def sum(self, axis=None, keepdims=False):
        return invoke("sum", [self], axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return invoke("mean", [self], axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return invoke("max", [self], axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return invoke("min", [self], axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return invoke("prod", [self], axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return invoke("norm", [self], ord=ord, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return invoke("argmax", [self], axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return invoke("argmin", [self], axis=axis, keepdims=keepdims)

    def argsort(self, axis=-1, is_ascend=True):
        return invoke("argsort", [self], axis=axis, is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return invoke("sort", [self], axis=axis, is_ascend=is_ascend)

    def topk(self, k=1, axis=-1, ret_typ="indices", is_ascend=False):
        return invoke("topk", [self], k=k, axis=axis, ret_typ=ret_typ,
                      is_ascend=is_ascend)

    def clip(self, a_min=None, a_max=None):
        return invoke("clip", [self], a_min=a_min, a_max=a_max)

    def nansum(self, axis=None, keepdims=False):
        return invoke("nansum", [self], axis=axis, keepdims=keepdims)

    def nanprod(self, axis=None, keepdims=False):
        return invoke("nanprod", [self], axis=axis, keepdims=keepdims)

    def round(self): return invoke("round", [self])
    def rint(self): return invoke("rint", [self])
    def fix(self): return invoke("fix", [self])
    def floor(self): return invoke("floor", [self])
    def ceil(self): return invoke("ceil", [self])
    def trunc(self): return invoke("trunc", [self])
    def diag(self, k=0): return invoke("diag", [self], k=k)

    def pad(self, mode="constant", pad_width=None, constant_value=0.0):
        return invoke("pad", [self], mode=mode,
                      pad_width=tuple(pad_width or ()),
                      constant_value=constant_value)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return invoke("split", [self], num_outputs=num_outputs,
                      axis=axis, squeeze_axis=squeeze_axis)

    def abs(self): return invoke("abs", [self])
    def exp(self): return invoke("exp", [self])
    def log(self): return invoke("log", [self])
    def sqrt(self): return invoke("sqrt", [self])
    def square(self): return invoke("square", [self])
    def sigmoid(self): return invoke("sigmoid", [self])
    def tanh(self): return invoke("tanh", [self])
    def relu(self): return invoke("relu", [self])
    def softmax(self, axis=-1): return invoke("softmax", [self], axis=axis)
    def log_softmax(self, axis=-1): return invoke("log_softmax", [self], axis=axis)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return invoke("dot", [self, other], transpose_a=transpose_a,
                      transpose_b=transpose_b)

    def slice(self, begin, end, step=None):
        return invoke("slice", [self], begin=begin, end=end, step=step)

    def slice_axis(self, axis, begin, end):
        return invoke("slice_axis", [self], axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return invoke("take", [self, indices], axis=axis, mode=mode)

    def one_hot(self, depth, on_value=1.0, off_value=0.0):
        return invoke("one_hot", [self], depth=depth, on_value=on_value,
                      off_value=off_value)

    def tile(self, reps): return invoke("tile", [self], reps=reps)

    def repeat(self, repeats, axis=None):
        return invoke("repeat", [self], repeats=repeats, axis=axis)

    def broadcast_to(self, shape):
        return invoke("broadcast_to", [self], shape=shape)

    def broadcast_like(self, other):
        return invoke("broadcast_like", [self, other])

    def flip(self, axis): return invoke("flip", [self], axis=axis)

    def tostype(self, stype):
        if stype == "default":
            return self
        from .sparse import cast_storage
        return cast_storage(self, stype)

    # numpy protocol
    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


def _norm_index(key, shape):
    """Normalize an index key: NDArray indices → jax arrays (int32)."""
    if isinstance(key, NDArray):
        return key._data.astype(jnp.int32) if jnp.issubdtype(
            key._data.dtype, jnp.number) else key._data
    if isinstance(key, tuple):
        return tuple(_norm_index(k, shape) for k in key)
    if isinstance(key, list):
        return onp.asarray(key)
    return key


# --------------------------------------------------------------------------
# factory functions (parity: init ops + ndarray utility functions)
# --------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None) -> NDArray:
    return NDArray(source_array, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    return NDArray(jnp.zeros(check_shape(shape), np_dtype(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype=None, **kwargs) -> NDArray:
    return NDArray(jnp.ones(check_shape(shape), np_dtype(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs) -> NDArray:
    return NDArray(jnp.full(check_shape(shape), val, np_dtype(dtype)), ctx=ctx)


def empty(shape, ctx=None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None) -> NDArray:
    out = jnp.arange(start, stop, step, np_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None) -> NDArray:
    return NDArray(jnp.linspace(start, stop, num, endpoint=endpoint,
                                dtype=np_dtype(dtype)), ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None) -> NDArray:
    return NDArray(jnp.eye(N, M if M else None, k, np_dtype(dtype)), ctx=ctx)


def concat(*arrays, dim=1):
    return invoke("concat", list(arrays), dim=dim)


def stack(*arrays, axis=0):
    return invoke("stack", list(arrays), axis=axis)


def waitall():
    from .. import engine
    engine.wait_all()


def from_numpy(a, zero_copy=False):
    return NDArray(a)


class _DLPackHandle:
    """Exchange handle speaking the modern DLPack protocol.  Both
    ``torch.from_dlpack`` and ``numpy.from_dlpack`` consume it, and
    unlike a raw one-shot capsule it can also report its device."""

    __slots__ = ("_arr",)

    def __init__(self, arr):
        self._arr = arr

    def __dlpack__(self, *args, **kwargs):
        return self._arr.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self._arr.__dlpack_device__()


def from_dlpack(obj):
    """Import a DLPack-capable array (torch/numpy tensor or a handle
    from :func:`to_dlpack_for_read` — the modern ``__dlpack__``
    protocol).  Raw one-shot capsules carry no device information and
    are rejected with a clear error."""
    if hasattr(obj, "__dlpack__"):
        return NDArray(jnp.from_dlpack(obj))
    raise TypeError(
        "from_dlpack needs an object with __dlpack__/__dlpack_device__ "
        "(a torch/numpy array or a to_dlpack_for_read handle), not a "
        "raw capsule")


def to_dlpack_for_read(arr: "NDArray"):
    """DLPack handle for the (synchronized) buffer (parity:
    mx.nd.to_dlpack_for_read over MXNDArrayToDLPack)."""
    arr.wait_to_read()
    return _DLPackHandle(arr._data)


def to_dlpack_for_write(arr: "NDArray"):
    """Parity: to_dlpack_for_write.  XLA buffers are immutable, so
    writes through the handle cannot alias back; consumers that
    mutate must re-import with from_dlpack (documented divergence)."""
    arr.wait_to_read()
    return _DLPackHandle(arr._data)


# -- serialization (parity: NDArray::Save/Load, src/ndarray/ndarray.cc:1679;
#    MXNDArraySave/Load C API).  Two codecs:
#      * "npz"   (default) — numpy .npz with a manifest key
#      * "mxnet" — the reference's binary wire format (ndarray.cc:1679),
#        byte-compatible with checkpoints produced by actual MXNet;
#        see legacy_serialization.py.  load() auto-detects by magic.
def save(fname: str, data, format: str = None):
    if format is None:
        import os
        format = os.environ.get("MXNET_NDARRAY_SAVE_FORMAT", "npz")
    if format in ("mxnet", "binary", "params"):
        from .legacy_serialization import save_mxnet
        return save_mxnet(fname, data)
    if format != "npz":
        raise MXNetError(f"save: unknown format {format!r} "
                         "(expected 'npz' or 'mxnet')")
    if isinstance(data, NDArray):
        payload, names = [data], ["__single__:0"]
    elif isinstance(data, (list, tuple)):
        payload, names = list(data), [f"__list__:{i}" for i in range(len(data))]
    elif isinstance(data, dict):
        payload, names = list(data.values()), [f"__dict__:{k}" for k in data]
    else:
        raise MXNetError("save: data must be NDArray, list, or dict")
    arrays = {}
    dtype_tags = {}
    for n, p in zip(names, payload):
        a = p.asnumpy()
        if a.dtype.kind == "V":
            # ml_dtypes (bfloat16 etc.): numpy has no native tag, so
            # store the raw bytes viewed as uint and remember the name
            dtype_tags[n] = str(p.dtype)
            a = a.view(onp.uint16 if a.dtype.itemsize == 2
                       else onp.uint8)
        arrays[n] = a
    if dtype_tags:
        import json as _json

        arrays["__dtypes__"] = onp.frombuffer(
            _json.dumps(dtype_tags).encode(), dtype=onp.uint8)
    if not payload:
        # disambiguate empty containers (an npz with no payload keys
        # would otherwise load as {})
        kind = "list" if isinstance(data, (list, tuple)) else "dict"
        arrays["__empty__"] = onp.frombuffer(kind.encode(),
                                             dtype=onp.uint8)
    # write to the exact filename (np.savez appends .npz to bare paths;
    # the reference's NDArray::Save writes the given name verbatim)
    with open(fname, "wb") as f:
        onp.savez(f, **arrays)


def load_frombuffer(buf):
    """Deserialize NDArrays from an in-memory buffer (parity:
    nd.load_frombuffer over MXNDArrayLoadFromBuffer,
    python/mxnet/ndarray/utils.py:185).  Accepts either codec: the
    reference binary wire format (by magic) or npz bytes."""
    from .legacy_serialization import is_mxnet_format, decode_list
    buf = bytes(buf)
    if is_mxnet_format(buf[:8]):
        data, names = decode_list(buf)
        return dict(zip(names, data)) if names else data
    import io
    return _load_npz(io.BytesIO(buf))


def load(fname: str):
    import os
    if not fname.endswith(".npz"):
        if os.path.exists(fname + ".npz") and not os.path.exists(fname):
            fname = fname + ".npz"
    if os.path.exists(fname):
        with open(fname, "rb") as f:
            head = f.read(8)
        from .legacy_serialization import is_mxnet_format, load_mxnet
        if is_mxnet_format(head):
            return load_mxnet(fname)
    return _load_npz(fname)


def _load_npz(path_or_filelike):
    """npz-codec loader shared by load() and load_frombuffer()."""
    with onp.load(path_or_filelike, allow_pickle=False) as z:
        keys = list(z.keys())
        dtype_tags = {}
        if "__empty__" in z:
            kind = bytes(z["__empty__"]).decode()
            return [] if kind == "list" else {}
        if "__dtypes__" in z:
            import json as _json

            dtype_tags = _json.loads(bytes(z["__dtypes__"]).decode())
            keys = [k for k in keys if k != "__dtypes__"]

        def restore(k):
            a = z[k]
            tag = dtype_tags.get(k)
            if tag is not None:
                import ml_dtypes  # noqa: F401 (registers dtype names)

                a = a.view(onp.dtype(tag))
            return NDArray(a)

        if keys and keys[0].startswith("__single__"):
            return restore(keys[0])
        if keys and keys[0].startswith("__list__"):
            order = sorted(keys, key=lambda k: int(k.split(":", 1)[1]))
            return [restore(k) for k in order]
        out = {}
        for k in keys:
            name = k.split(":", 1)[1] if ":" in k else k
            out[name] = restore(k)
        return out
