"""mx.nd.linalg — linear-algebra op namespace.

Parity: src/operator/tensor/la_op.cc (LAPACK/cuBLAS wrappers,
linalg_impl.h).  On TPU these lower through XLA's linalg ops; the MXU
handles the matmuls, the host/vector units the factorizations.
"""
from __future__ import annotations

import jax.numpy as jnp
import jax.scipy.linalg as jsl

from ..ops.registry import apply_jax

__all__ = ["gemm", "gemm2", "potrf", "potri", "trsm", "trmm", "syrk",
           "gelqf", "syevd", "sumlogdiag", "extractdiag", "makediag",
           "extracttrian", "maketrian", "inverse", "det", "slogdet"]


def gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0,
         axis=-2):
    def fn(a, b, c):
        ta = jnp.swapaxes(a, -1, -2) if transpose_a else a
        tb = jnp.swapaxes(b, -1, -2) if transpose_b else b
        return alpha * jnp.matmul(ta, tb) + beta * c
    return apply_jax(fn, [A, B, C])


def gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2):
    def fn(a, b):
        ta = jnp.swapaxes(a, -1, -2) if transpose_a else a
        tb = jnp.swapaxes(b, -1, -2) if transpose_b else b
        return alpha * jnp.matmul(ta, tb)
    return apply_jax(fn, [A, B])


def potrf(A, lower=True):
    return apply_jax(lambda a: jnp.linalg.cholesky(a) if lower else
                     jnp.swapaxes(jnp.linalg.cholesky(a), -1, -2), [A])


def potri(A, lower=True):
    def fn(a):
        eye = jnp.eye(a.shape[-1], dtype=a.dtype)
        inv = jsl.solve_triangular(a, eye, lower=lower)
        return jnp.matmul(jnp.swapaxes(inv, -1, -2), inv) if lower else \
            jnp.matmul(inv, jnp.swapaxes(inv, -1, -2))
    return apply_jax(fn, [A])


def trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    def fn(a, b):
        if rightside:
            x = jsl.solve_triangular(jnp.swapaxes(a, -1, -2),
                                     jnp.swapaxes(b, -1, -2),
                                     lower=not lower, trans=1 if transpose else 0)
            return alpha * jnp.swapaxes(x, -1, -2)
        return alpha * jsl.solve_triangular(a, b, lower=lower,
                                            trans=1 if transpose else 0)
    return apply_jax(fn, [A, B])


def trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0):
    def fn(a, b):
        tri = jnp.tril(a) if lower else jnp.triu(a)
        if transpose:
            tri = jnp.swapaxes(tri, -1, -2)
        return alpha * (jnp.matmul(b, tri) if rightside else jnp.matmul(tri, b))
    return apply_jax(fn, [A, B])


def syrk(A, transpose=False, alpha=1.0):
    def fn(a):
        at = jnp.swapaxes(a, -1, -2)
        return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))
    return apply_jax(fn, [A])


def gelqf(A):
    def fn(a):
        q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
        return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)
    return apply_jax(fn, [A], multi_out=True)


def syevd(A):
    def fn(a):
        w, v = jnp.linalg.eigh(a)
        return jnp.swapaxes(v, -1, -2), w
    return apply_jax(fn, [A], multi_out=True)


def sumlogdiag(A):
    return apply_jax(lambda a: jnp.sum(jnp.log(jnp.diagonal(a, axis1=-2, axis2=-1)),
                                       axis=-1), [A])


def extractdiag(A, offset=0):
    return apply_jax(lambda a: jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1),
                     [A])


def makediag(A, offset=0):
    return apply_jax(lambda a: jnp.vectorize(
        lambda v: jnp.diag(v, k=offset), signature="(n)->(m,m)")(a), [A])


def extracttrian(A, offset=0, lower=True):
    def fn(a):
        n = a.shape[-1]
        idx = jnp.tril_indices(n, k=offset) if lower else jnp.triu_indices(n, k=offset)
        return a[..., idx[0], idx[1]]
    return apply_jax(fn, [A])


def maketrian(A, offset=0, lower=True):
    def fn(a):
        m = a.shape[-1]
        # solve n(n+1)/2 = m for n (assumes offset=0)
        n = int((-1 + (1 + 8 * m) ** 0.5) // 2)
        idx = jnp.tril_indices(n, k=offset) if lower else jnp.triu_indices(n, k=offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        return out.at[..., idx[0], idx[1]].set(a)
    return apply_jax(fn, [A])


def inverse(A):
    return apply_jax(jnp.linalg.inv, [A])


def det(A):
    return apply_jax(jnp.linalg.det, [A])


def slogdet(A):
    return apply_jax(lambda a: tuple(jnp.linalg.slogdet(a)), [A], multi_out=True)
