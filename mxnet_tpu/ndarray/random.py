"""mx.nd.random — sampling factory functions.

Parity: python/mxnet/ndarray/random.py over src/operator/random/
samplers.  Stateless jax.random keys are drawn from the global seed
state (mxnet_tpu.ops.random); inside a CachedOp trace the key is a real
traced input.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import np_dtype, check_shape
from ..ops import random as _r
from ..ops.registry import get as _get, apply_jax
from .ndarray import NDArray
import functools

__all__ = ["uniform", "normal", "randn", "gamma", "exponential", "poisson",
           "negative_binomial", "generalized_negative_binomial", "randint",
           "multinomial", "bernoulli", "shuffle", "laplace", "rayleigh",
           "gumbel", "logistic", "seed"]

seed = _r.seed


def _sample(op_name, shape, dtype, ctx, extra_inputs=(), **params):
    shape = check_shape(shape if shape is not None else 1)
    key = _r.next_key()
    fn = functools.partial(_get(op_name).fn,
                           shape=shape, dtype=np_dtype(dtype), **params)
    key_nd = NDArray(key)
    return apply_jax(lambda k, *rest: fn(k, *rest),
                     [key_nd, *extra_inputs], record=False)


def uniform(low=0.0, high=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_uniform", shape, dtype, ctx, low=low, high=high)
    return out._adopt(r) if out is not None else r


def normal(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_normal", shape, dtype, ctx, loc=loc, scale=scale)
    return out._adopt(r) if out is not None else r


def randn(*shape, loc=0.0, scale=1.0, dtype=None, ctx=None):
    return normal(loc, scale, shape or (1,), dtype, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_gamma", shape, dtype, ctx, alpha=alpha, beta=beta)
    return out._adopt(r) if out is not None else r


def exponential(scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_exponential", shape, dtype, ctx, lam=1.0 / scale)
    return out._adopt(r) if out is not None else r


def poisson(lam=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_poisson", shape, dtype, ctx, lam=lam)
    return out._adopt(r) if out is not None else r


def negative_binomial(k=1, p=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_negative_binomial", shape, dtype, ctx, k=k, p=p)
    return out._adopt(r) if out is not None else r


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None, dtype=None,
                                  ctx=None, out=None, **kw):
    r = _sample("_random_generalized_negative_binomial", shape, dtype, ctx,
                mu=mu, alpha=alpha)
    return out._adopt(r) if out is not None else r


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None, **kw):
    r = _sample("_random_randint", shape, dtype, ctx, low=low, high=high)
    return out._adopt(r) if out is not None else r


def bernoulli(prob=0.5, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_bernoulli", shape, dtype, ctx, prob=prob)
    return out._adopt(r) if out is not None else r


def laplace(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_laplace", shape, dtype, ctx, loc=loc, scale=scale)
    return out._adopt(r) if out is not None else r


def rayleigh(scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_rayleigh", shape, dtype, ctx, scale=scale)
    return out._adopt(r) if out is not None else r


def gumbel(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_gumbel", shape, dtype, ctx, loc=loc, scale=scale)
    return out._adopt(r) if out is not None else r


def logistic(loc=0.0, scale=1.0, shape=None, dtype=None, ctx=None, out=None, **kw):
    r = _sample("_random_logistic", shape, dtype, ctx, loc=loc, scale=scale)
    return out._adopt(r) if out is not None else r


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    key = _r.next_key()
    fn = functools.partial(_get("_sample_multinomial").fn,
                           shape=shape, get_prob=get_prob, dtype=np_dtype(dtype))
    return apply_jax(lambda k, d: fn(k, d), [NDArray(key), data], record=False)


def shuffle(data, **kw):
    key = _r.next_key()
    return apply_jax(lambda k, d: _get("_shuffle").fn(k, d),
                     [NDArray(key), data], record=False)
