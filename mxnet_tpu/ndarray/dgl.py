"""DGL graph operators (neighbor sampling / induced subgraph / adjacency /
compaction).

Parity: src/operator/contrib/dgl_graph.cc —
``_contrib_dgl_csr_neighbor_uniform_sample`` (:761),
``_contrib_dgl_csr_neighbor_non_uniform_sample`` (:866),
``_contrib_dgl_subgraph`` (:1146), ``_contrib_dgl_adjacency`` (:1407),
``_contrib_dgl_graph_compact`` (:1582).

TPU-first notes: graph sampling is data-dependent, pointer-chasing host
work that *feeds* the accelerator (the sampled blocks become dense
gather/scatter + matmul on device) — the reference likewise runs these
only as CPU FComputeEx kernels over CSR storage.  Our sparse storage is
eager host-side (see ndarray/sparse.py), so these ops are vectorized
numpy over (indptr, indices, data), keeping the reference's exact output
contract: sampled-vertex arrays of length ``max_num_vertices+1`` whose
last element is the true count, per-subgraph CSRs with rows in
sorted-sampled-vertex order, and layer/probability side outputs.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as onp

from ..base import MXNetError
from .ndarray import NDArray
from .sparse import CSRNDArray

__all__ = ["dgl_csr_neighbor_uniform_sample",
           "dgl_csr_neighbor_non_uniform_sample",
           "dgl_subgraph", "dgl_adjacency", "dgl_graph_compact"]


def _csr_parts(csr: CSRNDArray):
    if not isinstance(csr, CSRNDArray):
        raise MXNetError("dgl ops expect a CSRNDArray graph, got "
                         f"{type(csr).__name__}")
    return (onp.asarray(csr.indptr, onp.int64),
            onp.asarray(csr.indices, onp.int64),
            onp.asarray(csr.data))


def _as_1d_int(arr) -> onp.ndarray:
    a = arr.asnumpy() if hasattr(arr, "asnumpy") else onp.asarray(arr)
    return onp.asarray(a, onp.int64).reshape(-1)


def _sample_subgraph(indptr, indices, data, seeds, probability,
                     num_hops, num_neighbor, max_num_vertices, rng):
    """BFS-sample one subgraph; returns (verts, layers, sub_csr parts,
    num real vertices).  Mirrors SampleSubgraph (dgl_graph.cc:539-723):
    dedup seeds at layer 0, expand each queued vertex whose layer <
    num_hops by sampling ≤ num_neighbor of its out-edges, stop growing
    once max_num_vertices distinct vertices are collected, then emit
    vertices sorted ascending with rows of the sub-CSR in that order."""
    if len(seeds) > max_num_vertices:
        raise MXNetError("max_num_vertices must be >= number of seeds")
    visited = {}
    queue: List[tuple] = []
    for s in seeds:
        s = int(s)
        if s not in visited:
            visited[s] = 0
            queue.append((s, 0))
    neigh = {}
    idx = 0
    # Every queued vertex below the hop limit gets its neighbors sampled;
    # the vertex budget only gates *adding* new vertices to the frontier
    # (the reference's inner-loop break, dgl_graph.cc:630-642 — sampled
    # edges are recorded even when their endpoint no longer fits).
    while idx < len(queue):
        vid, lvl = queue[idx]
        idx += 1
        if lvl >= num_hops:
            continue
        lo, hi = int(indptr[vid]), int(indptr[vid + 1])
        cols = indices[lo:hi]
        vals = data[lo:hi]
        deg = hi - lo
        if deg > num_neighbor:
            if probability is None:
                sel = onp.sort(rng.choice(deg, num_neighbor, replace=False))
                scols, svals = cols[sel], vals[sel]
            else:
                p = onp.asarray(probability, onp.float64)[cols]
                tot = p.sum()
                if tot <= 0:
                    raise MXNetError("probability mass of neighbors is 0")
                sel = rng.choice(deg, num_neighbor, replace=False, p=p / tot)
                # reference sorts sampled vertices and edges independently
                # after heap sampling (GetNonUniformSample,
                # dgl_graph.cc:507-520)
                scols = onp.sort(cols[sel])
                svals = onp.sort(vals[sel])
        else:
            scols, svals = cols, vals
        neigh[vid] = (scols, svals)
        for c in scols:
            c = int(c)
            if len(visited) >= max_num_vertices:
                break
            if c not in visited:
                visited[c] = lvl + 1
                queue.append((c, lvl + 1))

    order = sorted(visited)
    n = len(order)
    verts = onp.zeros(max_num_vertices + 1, onp.int64)
    layers = onp.zeros(max_num_vertices, onp.int64)
    verts[:n] = order
    verts[max_num_vertices] = n
    layers[:n] = [visited[v] for v in order]

    out_indptr = onp.zeros(max_num_vertices + 1, onp.int64)
    cols_l, vals_l = [], []
    for i, v in enumerate(order):
        if v in neigh:
            sc, sv = neigh[v]
            cols_l.append(sc)
            vals_l.append(sv)
            out_indptr[i + 1] = out_indptr[i] + len(sc)
        else:
            out_indptr[i + 1] = out_indptr[i]
    out_indptr[n + 1:] = out_indptr[n]
    out_cols = (onp.concatenate(cols_l).astype(onp.int64) if cols_l
                else onp.zeros(0, onp.int64))
    out_vals = (onp.concatenate(vals_l) if vals_l
                else onp.zeros(0, data.dtype))
    return verts, layers, (out_vals, out_cols, out_indptr), n


def _make_rng(seed=None):
    if seed is None:
        # derive host entropy from the global key chain so mx.random.seed
        # makes sampling reproducible (parity: kRandom resource seeding)
        import jax
        from ..ops.random import next_key
        seed = int(onp.asarray(
            jax.random.key_data(next_key())).ravel()[-1]) & 0x7FFFFFFF
    return onp.random.RandomState(seed)


def dgl_csr_neighbor_uniform_sample(csr, *seed_arrays, num_args=None,
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100, seed=None):
    """Uniform neighbor sampling over a CSR graph (parity:
    dgl_graph.cc:761).  Returns, for S seed arrays, a flat list
    ``[verts]*S + [sub_csr]*S + [layer]*S`` where each ``verts`` is
    int64 of length ``max_num_vertices+1`` (last element = true vertex
    count), ``sub_csr`` has shape ``(max_num_vertices, graph.shape[1])``
    with rows in sorted-vertex order, and ``layer`` gives each vertex's
    BFS layer."""
    indptr, indices, data = _csr_parts(csr)
    if num_args is not None and num_args != len(seed_arrays) + 1:
        raise MXNetError("num_args must equal 1 + number of seed arrays")
    rng = _make_rng(seed)
    verts_out, csr_out, layer_out = [], [], []
    for sarr in seed_arrays:
        verts, layers, (v, c, p), _ = _sample_subgraph(
            indptr, indices, data, _as_1d_int(sarr), None,
            num_hops, num_neighbor, max_num_vertices, rng)
        verts_out.append(NDArray(verts))
        csr_out.append(CSRNDArray(v, c, p,
                                  (max_num_vertices, csr.shape[1])))
        layer_out.append(NDArray(layers))
    return verts_out + csr_out + layer_out


def dgl_csr_neighbor_non_uniform_sample(csr, probability, *seed_arrays,
                                        num_args=None, num_hops=1,
                                        num_neighbor=2,
                                        max_num_vertices=100, seed=None):
    """Non-uniform (per-vertex probability) neighbor sampling (parity:
    dgl_graph.cc:866).  Output layout is
    ``[verts]*S + [sub_csr]*S + [prob]*S + [layer]*S`` where ``prob``
    holds the sampling probability of each sampled vertex."""
    indptr, indices, data = _csr_parts(csr)
    if num_args is not None and num_args != len(seed_arrays) + 2:
        raise MXNetError("num_args must equal 2 + number of seed arrays")
    prob = onp.asarray(
        probability.asnumpy() if hasattr(probability, "asnumpy")
        else probability, onp.float32).reshape(-1)
    rng = _make_rng(seed)
    verts_out, csr_out, prob_out, layer_out = [], [], [], []
    for sarr in seed_arrays:
        verts, layers, (v, c, p), n = _sample_subgraph(
            indptr, indices, data, _as_1d_int(sarr), prob,
            num_hops, num_neighbor, max_num_vertices, rng)
        sp = onp.zeros(max_num_vertices, onp.float32)
        sp[:n] = prob[verts[:n]]
        verts_out.append(NDArray(verts))
        csr_out.append(CSRNDArray(v, c, p,
                                  (max_num_vertices, csr.shape[1])))
        prob_out.append(NDArray(sp))
        layer_out.append(NDArray(layers))
    return verts_out + csr_out + prob_out + layer_out


def dgl_subgraph(graph, *vertex_arrays, num_args=None,
                 return_mapping=False):
    """Induced subgraph(s) for sorted vertex lists (parity:
    dgl_graph.cc:1146 GetSubgraph).  Vertices are renumbered
    0..len(v)-1; edge data in the primary output is the *new* edge id
    (dense row-major order); with ``return_mapping`` a second CSR per
    input carries the original edge ids."""
    indptr, indices, data = _csr_parts(graph)
    if num_args is not None and num_args != len(vertex_arrays) + 1:
        raise MXNetError("num_args must equal 1 + number of vertex arrays")
    subs, maps = [], []
    for varr in vertex_arrays:
        vids = _as_1d_int(varr)
        if not onp.all(vids[:-1] <= vids[1:]):
            raise MXNetError("the input vertex list has to be sorted")
        old2new = {int(v): i for i, v in enumerate(vids)}
        n = len(vids)
        out_indptr = onp.zeros(n + 1, onp.int64)
        cols_l, eids_l = [], []
        for i, v in enumerate(vids):
            lo, hi = int(indptr[v]), int(indptr[v + 1])
            row_cols = indices[lo:hi]
            keep = [j for j, c in enumerate(row_cols) if int(c) in old2new]
            cols_l.append(onp.asarray(
                [old2new[int(row_cols[j])] for j in keep], onp.int64))
            eids_l.append(data[lo:hi][keep])
            out_indptr[i + 1] = out_indptr[i] + len(keep)
        cols = (onp.concatenate(cols_l).astype(onp.int64) if cols_l
                else onp.zeros(0, onp.int64))
        orig = (onp.concatenate(eids_l) if eids_l
                else onp.zeros(0, data.dtype))
        new_ids = onp.arange(len(cols), dtype=data.dtype)
        subs.append(CSRNDArray(new_ids, cols, out_indptr, (n, n)))
        if return_mapping:
            maps.append(CSRNDArray(orig, cols.copy(), out_indptr.copy(),
                                   (n, n)))
    return subs + maps if return_mapping else subs


def dgl_adjacency(csr):
    """CSR of edge ids → CSR adjacency of float32 ones (parity:
    dgl_graph.cc:1407)."""
    indptr, indices, data = _csr_parts(csr)
    return CSRNDArray(onp.ones(len(data), onp.float32), indices.copy(),
                      indptr.copy(), csr.shape)


def dgl_graph_compact(*graph_data, graph_sizes, return_mapping=False,
                      num_args=None):
    """Compact sampler-produced CSRs (parity: dgl_graph.cc:1582
    CompactSubgraph): drop trailing empty rows and renumber columns by
    each graph's sampled-vertex list.

    Inputs are ``g0..g{S-1}, vids0..vids{S-1}`` where each ``vids`` is
    the sampler's vertex output (last element = true count, which must
    equal the corresponding ``graph_sizes`` entry).  Primary outputs
    hold new edge ids 0..nnz-1; with ``return_mapping`` the second set
    keeps the input CSR's edge values (the reference declares this
    output but leaves it unwritten — we fill it with the original
    values, the useful contract)."""
    if num_args is not None and num_args != len(graph_data):
        raise MXNetError("num_args must equal number of graph_data inputs")
    if len(graph_data) % 2 != 0:
        raise MXNetError("graph_data must be graphs followed by vid arrays")
    num_g = len(graph_data) // 2
    sizes = ([int(s) for s in graph_sizes]
             if isinstance(graph_sizes, (list, tuple, onp.ndarray))
             else [int(graph_sizes)] * num_g)
    if len(sizes) != num_g:
        raise MXNetError("graph_sizes must have one entry per graph")
    outs, maps = [], []
    for i in range(num_g):
        indptr, indices, data = _csr_parts(graph_data[i])
        vids = _as_1d_int(graph_data[i + num_g])
        size = sizes[i]
        if int(vids[-1]) != size:
            raise MXNetError(
                f"graph_sizes[{i}]={size} does not match the vertex "
                f"count {int(vids[-1])} recorded in the vid array")
        id_map = {int(v): j for j, v in enumerate(vids[:size])}
        new_indptr = indptr[:size + 1].copy()
        nnz = int(new_indptr[-1])
        try:
            new_cols = onp.asarray([id_map[int(c)] for c in indices[:nnz]],
                                   onp.int64)
        except KeyError as e:
            # the sampler records edges whose endpoint no longer fit the
            # vertex budget (see dgl.py _sample_subgraph); such a graph
            # cannot be compacted — reference CHECK-fails the same way
            # (dgl_graph.cc:1498 CHECK(it != id_map.end()))
            raise MXNetError(
                f"graph {i} has an edge to vertex {e.args[0]} that is "
                "not in its sampled-vertex list (sampling was truncated "
                "by max_num_vertices); raise max_num_vertices so all "
                "edge endpoints fit, or drop these edges before "
                "compacting") from None
        outs.append(CSRNDArray(onp.arange(nnz, dtype=onp.int64), new_cols,
                               new_indptr, (size, size)))
        if return_mapping:
            maps.append(CSRNDArray(data[:nnz].copy(), new_cols.copy(),
                                   new_indptr.copy(), (size, size)))
    return outs + maps if return_mapping else outs
