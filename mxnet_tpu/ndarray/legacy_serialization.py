"""Reference-compatible binary NDArray serialization (the ``.params``
wire format).

Parity: ``NDArray::Save/Load`` (src/ndarray/ndarray.cc:1679,1802,1914),
``NDArray::Save(Stream, vector<NDArray>, vector<string>)`` list format
(ndarray.cc:1925, kMXAPINDArrayListMagic 0x112), ``Tuple::Save/Load``
(include/mxnet/tuple.h:731,745), ``Context::Save/Load``
(include/mxnet/base.h:145,154).  This is the format every checkpoint in
the MXNet ecosystem is stored in (gluon ``save_parameters``,
``export()``, the pretrained model zoo, ``mx.nd.save``), guarded
upstream by ``tests/nightly/model_backwards_compatibility_check/``.

Implemented from the format spec (byte layout re-derived from the
reference sources cited above — no code copied):

file      := uint64 magic=0x112 | uint64 reserved=0
           | uint64 n_arrays | ndarray*  | uint64 n_names | name*
name      := uint64 len | bytes          (dmlc::Stream vector<string>)
ndarray   := uint32 magic (V1 0xF993fac8 / V2 0xF993fac9 / V3 0xF993faca
                           / legacy: magic IS ndim, uint32 dims follow)
           | [V2/V3] int32 stype
           | [stype sparse] tshape storage_shape
           | tshape shape                (empty shape => none, stop)
           | int32 dev_type, int32 dev_id
           | int32 type_flag             (mshadow dtype enum)
           | [sparse, per aux] int32 aux_type | tshape aux_shape
           | raw data  (C-order, little-endian, storage_shape elems)
           | [sparse, per aux] raw aux data
tshape    := int32 ndim | int64 dim[ndim]

All integers little-endian (the reference writes host byte order and
ships x86 artifacts; we fix LE explicitly so the codec is
platform-stable).
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as onp

from ..base import MXNetError

NDARRAY_V1_MAGIC = 0xF993FAC8
NDARRAY_V2_MAGIC = 0xF993FAC9
NDARRAY_V3_MAGIC = 0xF993FACA
LIST_MAGIC = 0x112

# storage types (include/mxnet/ndarray.h:61-65)
K_DEFAULT_STORAGE = 0
K_ROW_SPARSE_STORAGE = 1
K_CSR_STORAGE = 2

# device types (include/mxnet/base.h:92-97)
K_CPU = 1

# mshadow dtype enum (3rdparty/mshadow/mshadow/base.h:329-341)
_TYPE_FLAG_TO_DTYPE = {
    0: "float32", 1: "float64", 2: "float16", 3: "uint8", 4: "int32",
    5: "int8", 6: "int64", 7: "bool", 8: "int16", 9: "uint16",
    10: "uint32", 11: "uint64", 12: "bfloat16",
}
_DTYPE_TO_TYPE_FLAG = {v: k for k, v in _TYPE_FLAG_TO_DTYPE.items()}


def _np_dtype(name: str) -> onp.dtype:
    if name == "bfloat16":
        import ml_dtypes
        return onp.dtype(ml_dtypes.bfloat16)
    return onp.dtype(name)


def _dtype_flag(dt) -> int:
    name = onp.dtype(dt).name
    if name == "void16":  # ml_dtypes viewed through plain numpy
        name = str(dt)
    if name not in _DTYPE_TO_TYPE_FLAG:
        raise MXNetError(
            f"dtype {name} has no representation in the MXNet binary "
            f"format (mshadow enum); cast before saving")
    return _DTYPE_TO_TYPE_FLAG[name]


class _Writer:
    def __init__(self):
        self.parts: List[bytes] = []

    def u32(self, v): self.parts.append(struct.pack("<I", v))
    def i32(self, v): self.parts.append(struct.pack("<i", v))
    def u64(self, v): self.parts.append(struct.pack("<Q", v))
    def raw(self, b): self.parts.append(bytes(b))

    def tshape(self, dims):
        self.i32(len(dims))
        for d in dims:
            self.parts.append(struct.pack("<q", int(d)))

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.off = 0

    def _take(self, n: int) -> bytes:
        if self.off + n > len(self.buf):
            raise MXNetError("invalid NDArray file format (truncated)")
        b = self.buf[self.off:self.off + n]
        self.off += n
        return b

    def u32(self): return struct.unpack("<I", self._take(4))[0]
    def i32(self): return struct.unpack("<i", self._take(4))[0]
    def u64(self): return struct.unpack("<Q", self._take(8))[0]

    def tshape(self, v1_uint32: bool = False, ndim: Optional[int] = None):
        if ndim is None:
            ndim = self.i32()
        if ndim < 0:
            return None  # unknown shape (np semantics "none")
        fmt, width = ("<I", 4) if v1_uint32 else ("<q", 8)
        return tuple(struct.unpack(fmt, self._take(width))[0]
                     for _ in range(ndim))

    def array(self, dtype: onp.dtype, shape) -> onp.ndarray:
        n = 1
        for d in shape:
            n *= int(d)
        raw = self._take(n * dtype.itemsize)
        a = onp.frombuffer(raw, dtype=dtype.newbyteorder("<")).astype(
            dtype, copy=False)
        return a.reshape(shape)


def _num_aux(stype: int) -> int:
    try:
        return {K_DEFAULT_STORAGE: 0, K_ROW_SPARSE_STORAGE: 1,
                K_CSR_STORAGE: 2}[stype]
    except KeyError:
        raise MXNetError(
            f"invalid NDArray file format (unknown storage type "
            f"{stype})") from None


def encode_ndarray(arr) -> bytes:
    """Serialize one array in the reference wire format.  Accepts a
    dense NDArray, RowSparseNDArray, or CSRNDArray."""
    from .ndarray import NDArray
    from .sparse import RowSparseNDArray, CSRNDArray

    out = _Writer()

    if isinstance(arr, RowSparseNDArray):
        values = onp.ascontiguousarray(onp.asarray(arr.data.asnumpy()
                  if isinstance(arr.data, NDArray) else arr.data))
        idx = onp.ascontiguousarray(
            onp.asarray(arr.indices.asnumpy()
                        if isinstance(arr.indices, NDArray)
                        else arr.indices)).astype(onp.int64)
        out.u32(NDARRAY_V2_MAGIC)
        out.i32(K_ROW_SPARSE_STORAGE)
        out.tshape(values.shape)       # storage shape
        out.tshape(arr.shape)          # logical shape
        out.i32(K_CPU); out.i32(0)     # context
        out.i32(_dtype_flag(values.dtype))
        out.i32(_DTYPE_TO_TYPE_FLAG["int64"])  # aux type (kIdx)
        out.tshape(idx.shape)
        out.raw(values.astype(values.dtype.newbyteorder("<")).tobytes())
        out.raw(idx.astype("<i8").tobytes())
    elif isinstance(arr, CSRNDArray):
        values = onp.ascontiguousarray(onp.asarray(
            arr.data.asnumpy() if isinstance(arr.data, NDArray)
            else arr.data))
        indptr = onp.ascontiguousarray(onp.asarray(
            arr.indptr.asnumpy() if isinstance(arr.indptr, NDArray)
            else arr.indptr)).astype(onp.int64)
        idx = onp.ascontiguousarray(onp.asarray(
            arr.indices.asnumpy() if isinstance(arr.indices, NDArray)
            else arr.indices)).astype(onp.int64)
        out.u32(NDARRAY_V2_MAGIC)
        out.i32(K_CSR_STORAGE)
        out.tshape(values.shape)
        out.tshape(arr.shape)
        out.i32(K_CPU); out.i32(0)
        out.i32(_dtype_flag(values.dtype))
        # aux order: kIndPtr, kIdx (include/mxnet/ndarray.h:54)
        out.i32(_DTYPE_TO_TYPE_FLAG["int64"]); out.tshape(indptr.shape)
        out.i32(_DTYPE_TO_TYPE_FLAG["int64"]); out.tshape(idx.shape)
        out.raw(values.astype(values.dtype.newbyteorder("<")).tobytes())
        out.raw(indptr.astype("<i8").tobytes())
        out.raw(idx.astype("<i8").tobytes())
    else:
        a = arr.asnumpy() if isinstance(arr, NDArray) else onp.asarray(arr)
        # NOT ascontiguousarray: that promotes 0-dim scalars to 1-dim
        a = onp.asarray(a, order="C")
        # 0-dim arrays only exist under np shape semantics => V3 magic
        # (ndarray.cc: V2 treats ndim==0 as "none")
        out.u32(NDARRAY_V3_MAGIC if a.ndim == 0 else NDARRAY_V2_MAGIC)
        out.i32(K_DEFAULT_STORAGE)
        out.tshape(a.shape)
        out.i32(K_CPU); out.i32(0)
        out.i32(_dtype_flag(a.dtype))
        if a.dtype.kind == "V":  # bfloat16 via ml_dtypes: raw LE bytes
            out.raw(a.tobytes())
        else:
            out.raw(a.astype(a.dtype.newbyteorder("<")).tobytes())
    return out.getvalue()


def decode_ndarray(r: _Reader):
    """Inverse of encode_ndarray; also reads V1 and pre-V1 legacy
    records (ndarray.cc LegacyLoad:1760)."""
    from .ndarray import NDArray
    from .sparse import RowSparseNDArray, CSRNDArray

    magic = r.u32()
    if magic not in (NDARRAY_V2_MAGIC, NDARRAY_V3_MAGIC):
        # legacy record: V1 has an int64 tshape; anything else means the
        # magic itself was the ndim of a uint32 shape
        if magic == NDARRAY_V1_MAGIC:
            shape = r.tshape()
        else:
            shape = r.tshape(v1_uint32=True, ndim=magic)
        if shape is None or len(shape) == 0:
            return NDArray(onp.zeros((0,), onp.float32))
        r.i32(); r.i32()  # context
        dtype = _np_dtype(_TYPE_FLAG_TO_DTYPE[r.i32()])
        return NDArray(r.array(dtype, shape))

    stype = r.i32()
    nad = _num_aux(stype)
    storage_shape = r.tshape() if nad > 0 else None
    shape = r.tshape()
    if shape is None or (magic == NDARRAY_V2_MAGIC and len(shape) == 0):
        return NDArray(onp.zeros((0,), onp.float32))
    r.i32(); r.i32()  # context (always materialized on default device)
    dtype = _np_dtype(_TYPE_FLAG_TO_DTYPE[r.i32()])
    aux = []
    for _ in range(nad):
        aux_dtype = _np_dtype(_TYPE_FLAG_TO_DTYPE[r.i32()])
        aux_shape = r.tshape()
        aux.append((aux_dtype, aux_shape))
    data = r.array(dtype, storage_shape if nad > 0 else shape)
    aux_data = [r.array(dt, shp) for dt, shp in aux]
    if stype == K_ROW_SPARSE_STORAGE:
        return RowSparseNDArray(data, aux_data[0], shape)
    if stype == K_CSR_STORAGE:
        return CSRNDArray(data, aux_data[1], aux_data[0], shape)
    return NDArray(data)


def encode_list(payload, names: List[str]) -> bytes:
    w = _Writer()
    w.u64(LIST_MAGIC)
    w.u64(0)  # reserved
    w.u64(len(payload))
    for p in payload:
        w.raw(encode_ndarray(p))
    w.u64(len(names))
    for n in names:
        b = n.encode("utf-8")
        w.u64(len(b))
        w.raw(b)
    return w.getvalue()


def decode_list(buf: bytes):
    r = _Reader(buf)
    if r.u64() != LIST_MAGIC:
        raise MXNetError("invalid NDArray file format (bad magic)")
    r.u64()  # reserved
    n = r.u64()
    data = [decode_ndarray(r) for _ in range(n)]
    n_names = r.u64()
    names = []
    for _ in range(n_names):
        ln = r.u64()
        names.append(r._take(ln).decode("utf-8"))
    if names and len(names) != len(data):
        raise MXNetError("invalid NDArray file format (name count)")
    return data, names


def is_mxnet_format(head: bytes) -> bool:
    """Sniff the 8-byte list magic (npz files start with 'PK')."""
    return len(head) >= 8 and \
        struct.unpack("<Q", head[:8])[0] == LIST_MAGIC


def save_mxnet(fname: str, data):
    """mx.nd.save with the reference binary codec.  A bare NDArray is
    stored as a 1-element unnamed list — the reference format has no
    single-array marker (C API MXNDArraySave always writes a list)."""
    from .ndarray import NDArray
    from .sparse import BaseSparseNDArray
    if isinstance(data, (NDArray, BaseSparseNDArray)):
        payload, names = [data], []
    elif isinstance(data, (list, tuple)):
        payload, names = list(data), []
    elif isinstance(data, dict):
        payload, names = list(data.values()), list(data.keys())
    else:
        raise MXNetError("save: data must be NDArray, list, or dict")
    with open(fname, "wb") as f:
        f.write(encode_list(payload, names))


def load_mxnet(fname: str):
    with open(fname, "rb") as f:
        buf = f.read()
    data, names = decode_list(buf)
    if not names:
        return data
    return dict(zip(names, data))
