"""mx.nd — the imperative NDArray namespace.

Op functions are generated from the registry at import time, exactly as
the reference code-gens this module from its C op registry
(python/mxnet/ndarray/register.py).
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      linspace, eye, concat, stack, waitall, save, load,
                      load_frombuffer, from_numpy, from_dlpack,
                      to_dlpack_for_read, to_dlpack_for_write)
from .register import populate_namespace, make_op_func
from . import random
from . import linalg
from . import contrib
from . import sparse
from . import image
from .sparse import cast_storage
from .random import shuffle
import sys as _sys
op = _sys.modules[__name__]   # parity: mx.nd.op aliases the op namespace

populate_namespace(globals())

# reference-compat names
def Dropout(data, *args, p=0.5, mode="training", axes=(), key=None,
            **kwargs):
    """Eager Dropout with the reference's mode semantics: "training"
    applies only under autograd.record(train_mode=True), "always"
    applies unconditionally.  Positional args follow the reference
    signature ``Dropout(data, p, mode, axes)``; an NDArray in the
    first positional slot is accepted as an explicit PRNG ``key``
    (the engine-supplied RNG resource is otherwise a key drawn from
    the global chain)."""
    from .. import autograd as ag
    from ..ops.random import next_key
    from ..ops.registry import invoke

    pos = list(args)
    if pos and isinstance(pos[0], NDArray):
        key = pos.pop(0)
    for name, val in zip(("p", "mode", "axes"), pos):
        if name == "p":
            p = val
        elif name == "mode":
            mode = val
        else:
            axes = val
    if p <= 0 or (mode != "always" and not ag.is_training()):
        return data
    if key is None:
        key = NDArray(next_key())
    return invoke("Dropout", [data, key], p=p, axes=tuple(axes))


dropout = Dropout


def zeros_like(a):  # noqa: F811 — registry version takes NDArray only too
    from ..ops.registry import invoke
    return invoke("zeros_like", [a])


def ones_like(a):
    from ..ops.registry import invoke
    return invoke("ones_like", [a])


def Custom(*inputs, op_type, **kwargs):
    """Invoke a Python CustomOp (parity: mx.nd.Custom, operator.py)."""
    from ..operator import Custom as _custom
    return _custom(*inputs, op_type=op_type, **kwargs)
