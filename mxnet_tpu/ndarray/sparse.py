"""Sparse NDArray storage types: row_sparse and csr.

Parity: ``include/mxnet/ndarray.h:61-65`` (kDefaultStorage /
kRowSparseStorage / kCSRStorage), ``src/operator/tensor/cast_storage``,
sparse dot (``src/operator/tensor/dot-inl.h``), ``sparse_retain``, and
the python surface ``python/mxnet/ndarray/sparse.py``.

TPU-native notes: sparse layouts live as (data, indices[, indptr])
device arrays; compute that benefits from the MXU densifies per-block
(csr·dense dot goes through jax.experimental.sparse BCOO, which XLA
lowers to gather/segment-sum), while row_sparse exists mainly as the
*gradient* format for embedding-style updates — its purpose is to make
optimizer updates touch only the live rows (scatter-apply), which is
exactly how the reference uses it (sgd/adam `_update` row_sparse
kernels, optimizer_op.cc).

Sparse tensors are eager-only containers (nnz is data-dependent —
incompatible with XLA static shapes); converting to dense re-enters
the jit world.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as onp
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..base import MXNetError, np_dtype
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "zeros", "array",
           "cast_storage", "retain", "dot", "add", "where_rows",
           "coalesce_rows"]


def _log_storage_fallback(what: str):
    """Parity: MXNET_STORAGE_FALLBACK_LOG_VERBOSE (env_var.md) — warn
    when a sparse array is densified to run an op that has no sparse
    kernel (the reference's "operator fallback to dense" log,
    src/executor/infer_graph_attr_pass.cc storage fallback)."""
    import os
    if os.environ.get("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", "0") not in \
            ("0", ""):
        import warnings
        warnings.warn(
            f"storage fallback: {what} densified (generated dense output "
            f"instead of sparse)", stacklevel=3)


class BaseSparseNDArray:
    """Common surface shared by both sparse storage types."""

    stype = "undefined"

    def __init__(self, shape: Tuple[int, ...], dtype):
        self._shape = tuple(int(s) for s in shape)
        self._dtype = onp.dtype(dtype)

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    def asnumpy(self) -> onp.ndarray:
        return self.todense().asnumpy()

    def wait_to_read(self):
        pass

    def __repr__(self):
        return (f"\n<{type(self).__name__} "
                f"{'x'.join(map(str, self.shape))} nnz={self.nnz}>")

    def tostype(self, stype: str):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)

    def copyto(self, other):
        if isinstance(other, NDArray):
            other._rebind(self.todense()._data)
            return other
        if isinstance(other, type(self)):
            for attr in ("data", "indices", "indptr"):
                if hasattr(self, attr):
                    setattr(other, attr, getattr(self, attr))
            other._shape = tuple(self.shape)
            other._dtype = self.dtype
            return other
        raise MXNetError("copyto: unsupported target for sparse")

    def copy(self):
        import copy as _copy
        return _copy.copy(self)


class RowSparseNDArray(BaseSparseNDArray):
    """Rows `indices` hold `data`; all other rows are zero
    (parity: ndarray.h kRowSparseStorage; python sparse.py
    RowSparseNDArray)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape):
        data = jnp.asarray(data)
        indices = jnp.asarray(indices, jnp.int32)
        super().__init__(shape, data.dtype)
        if data.shape[1:] != tuple(shape[1:]):
            raise MXNetError(
                f"row_sparse data row shape {data.shape[1:]} != "
                f"array row shape {tuple(shape[1:])}")
        if data.shape[0] != indices.shape[0]:
            raise MXNetError("row_sparse data/indices length mismatch")
        self.data = data          # (nnz_rows, *row_shape)
        self.indices = indices    # (nnz_rows,) sorted

    @property
    def nnz(self):
        return int(self.data.shape[0])

    def todense(self) -> NDArray:
        _log_storage_fallback("row_sparse")
        out = jnp.zeros(self.shape, self.dtype)
        if self.nnz:
            out = out.at[self.indices].set(self.data)
        return NDArray(out)

    def retain(self, indices) -> "RowSparseNDArray":
        return retain(self, indices)

    def __neg__(self):
        return RowSparseNDArray(-self.data, self.indices, self.shape)

    def __mul__(self, scalar):
        return RowSparseNDArray(self.data * scalar, self.indices, self.shape)

    __rmul__ = __mul__

    def __truediv__(self, scalar):
        return RowSparseNDArray(self.data / scalar, self.indices, self.shape)

    def __add__(self, other):
        return add(self, other)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row 2-D matrix (parity: kCSRStorage)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape):
        data = jnp.asarray(data)
        super().__init__(shape, data.dtype)
        if len(shape) != 2:
            raise MXNetError("csr storage is 2-D only")
        self.data = data                                  # (nnz,)
        self.indices = jnp.asarray(indices, jnp.int32)    # (nnz,) col idx
        self.indptr = jnp.asarray(indptr, jnp.int32)      # (rows+1,)

    @property
    def nnz(self):
        return int(self.data.shape[0])

    def todense(self) -> NDArray:
        _log_storage_fallback("csr")
        rows, cols = self.shape
        counts = self.indptr[1:] - self.indptr[:-1]
        row_ids = jnp.repeat(jnp.arange(rows), counts,
                             total_repeat_length=self.nnz)
        out = jnp.zeros(self.shape, self.dtype)
        if self.nnz:
            out = out.at[row_ids, self.indices].set(self.data)
        return NDArray(out)

    def _to_bcoo(self) -> jsparse.BCOO:
        rows = self.shape[0]
        counts = self.indptr[1:] - self.indptr[:-1]
        row_ids = jnp.repeat(jnp.arange(rows), counts,
                             total_repeat_length=self.nnz)
        idx = jnp.stack([row_ids, self.indices], axis=1)
        return jsparse.BCOO((self.data, idx), shape=self.shape)

    def __getitem__(self, i):
        if isinstance(i, slice):
            if i == slice(None):
                return self
            raise MXNetError("csr slicing supports full slice only")
        if i < 0:
            i += self.shape[0]
        if not 0 <= i < self.shape[0]:
            raise IndexError(f"row {i} out of bounds for {self.shape}")
        lo, hi = int(self.indptr[i]), int(self.indptr[i + 1])
        out = onp.zeros((1, self.shape[1]), self.dtype)
        cols = onp.asarray(self.indices[lo:hi])
        out[0, cols] = onp.asarray(self.data[lo:hi])
        return _dense_array(out)


# --------------------------------------------------------------------------
# constructors (parity: mx.nd.sparse.row_sparse_array / csr_matrix)
# --------------------------------------------------------------------------

def row_sparse_array(arg, shape=None, dtype=None) -> RowSparseNDArray:
    if isinstance(arg, RowSparseNDArray):
        return arg
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        data = jnp.asarray(data, np_dtype(dtype) if dtype else None)
        if shape is None:
            raise MXNetError("row_sparse_array((data, indices)) needs shape")
        return RowSparseNDArray(data, indices, shape)
    # dense source
    dense = arg.asnumpy() if isinstance(arg, NDArray) else onp.asarray(arg)
    return cast_storage(_dense_array(dense.astype(
        np_dtype(dtype) if dtype else dense.dtype)), "row_sparse")


def csr_matrix(arg, shape=None, dtype=None) -> CSRNDArray:
    if isinstance(arg, CSRNDArray):
        return arg
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        if shape is None:
            raise MXNetError("csr_matrix((data, indices, indptr)) needs shape")
        return CSRNDArray(jnp.asarray(
            data, np_dtype(dtype) if dtype else None), indices, indptr, shape)
    dense = arg.asnumpy() if isinstance(arg, NDArray) else onp.asarray(arg)
    return cast_storage(_dense_array(dense.astype(
        np_dtype(dtype) if dtype else dense.dtype)), "csr")


def zeros(stype: str, shape, ctx=None, dtype=None):
    dt = np_dtype(dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int32), shape)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), jnp.zeros((0,), jnp.int32),
                          jnp.zeros((shape[0] + 1,), jnp.int32), shape)
    if stype == "default":
        from .ndarray import zeros as dzeros
        return dzeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown storage type {stype!r}")


def array(source, stype="default", shape=None, dtype=None):
    if stype == "row_sparse":
        return row_sparse_array(source, shape=shape, dtype=dtype)
    if stype == "csr":
        return csr_matrix(source, shape=shape, dtype=dtype)
    return _dense_array(source, dtype=dtype)


# --------------------------------------------------------------------------
# cast_storage (parity: src/operator/tensor/cast_storage-inl.h)
# --------------------------------------------------------------------------

def cast_storage(arr, stype: str):
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr
    a = arr.asnumpy()
    if stype == "row_sparse":
        axes = tuple(range(1, a.ndim))
        nz = onp.where(a.any(axis=axes) if axes else a != 0)[0]
        return RowSparseNDArray(a[nz], nz.astype(onp.int32), a.shape)
    if stype == "csr":
        if a.ndim != 2:
            raise MXNetError("csr storage is 2-D only")
        rows, cols = onp.nonzero(a)
        indptr = onp.zeros(a.shape[0] + 1, onp.int32)
        counts = onp.bincount(rows, minlength=a.shape[0])
        indptr[1:] = onp.cumsum(counts)
        return CSRNDArray(a[rows, cols], cols.astype(onp.int32),
                          indptr, a.shape)
    raise MXNetError(f"unknown storage type {stype!r}")


# --------------------------------------------------------------------------
# sparse ops (parity: sparse_retain, dot-inl.h sparse paths, elemwise add)
# --------------------------------------------------------------------------

def retain(rsp: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Keep only the requested rows (parity: _sparse_retain op)."""
    if isinstance(indices, NDArray):
        indices = indices.asnumpy()
    want = onp.asarray(indices, onp.int32)
    have = onp.asarray(rsp.indices)
    keep_mask = onp.isin(have, want)
    keep = onp.where(keep_mask)[0]
    return RowSparseNDArray(rsp.data[keep], have[keep], rsp.shape)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot: csr·dense, csr^T·dense, rsp'·dense
    (parity: dot-inl.h FInferStorageType dispatch table)."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray):
        bcoo = lhs._to_bcoo()
        if transpose_a:
            out = jsparse.bcoo_dot_general(
                bcoo, rhs._data, dimension_numbers=(((0,), (0,)), ((), ())))
        else:
            out = jsparse.bcoo_dot_general(
                bcoo, rhs._data, dimension_numbers=(((1,), (0,)), ((), ())))
        return NDArray(out)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray):
        # rsp^T · dense → row_sparse rows gather-matmul
        if not transpose_a:
            return NDArray(jnp.matmul(lhs.todense()._data, rhs._data))
        out = jnp.zeros((lhs.shape[1], rhs.shape[1]),
                        jnp.result_type(lhs.dtype, rhs.dtype))
        if lhs.nnz:
            picked = rhs._data[lhs.indices]
            out = jnp.einsum("nr,nc->rc", lhs.data, picked)
        return NDArray(out)
    if isinstance(lhs, NDArray) and isinstance(rhs, NDArray):
        from ..ops.registry import invoke
        return invoke("dot", [lhs, rhs], transpose_a=transpose_a,
                      transpose_b=transpose_b)
    raise MXNetError(
        f"dot: unsupported storage combination "
        f"({getattr(lhs, 'stype', 'default')}, "
        f"{getattr(rhs, 'stype', 'default')})")


def add(lhs, rhs):
    """Elementwise add across storage types."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        return merge(lhs, rhs)  # device-side union + segment_sum
    a = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return a + b


def where_rows(rsp: RowSparseNDArray) -> NDArray:
    """Indices of non-zero rows (parity: indices attribute access)."""
    return NDArray(rsp.indices)


# --------------------------------------------------------------------------
# sparse optimizer updates (parity: optimizer_op.cc row_sparse kernels —
# sgd_update:501 / adam_update:649 sparse paths, lazy_update semantics)
# --------------------------------------------------------------------------

def sgd_update(weight: NDArray, grad: RowSparseNDArray, lr: float,
               wd: float = 0.0, rescale_grad: float = 1.0,
               clip_gradient: float = -1.0) -> NDArray:
    """Apply SGD only to rows present in the row_sparse gradient."""
    g = grad.data * rescale_grad
    if clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    rows = grad.indices
    w_rows = weight._data[rows]
    new_rows = w_rows - lr * (g + wd * w_rows)
    weight._rebind(weight._data.at[rows].set(new_rows))
    return weight


def sgd_mom_update(weight: NDArray, grad: RowSparseNDArray, mom: NDArray,
                   lr: float, momentum: float = 0.9, wd: float = 0.0,
                   rescale_grad: float = 1.0) -> NDArray:
    """Lazy momentum update: momentum decays only on live rows
    (parity: sgd_mom row_sparse 'lazy_update' semantics)."""
    rows = grad.indices
    g = grad.data * rescale_grad + wd * weight._data[rows]
    m_rows = momentum * mom._data[rows] - lr * g
    mom._rebind(mom._data.at[rows].set(m_rows))
    weight._rebind(weight._data.at[rows].add(m_rows))
    return weight


def adagrad_update(weight: NDArray, grad: RowSparseNDArray, history: NDArray,
                   lr: float, epsilon: float = 1e-7, wd: float = 0.0,
                   rescale_grad: float = 1.0) -> NDArray:
    """Row-sparse AdaGrad (parity: _sparse_adagrad_update,
    src/operator/contrib/optimizer_op.cc group_adagrad)."""
    rows = grad.indices
    g = grad.data * rescale_grad
    if wd:
        g = g + wd * weight._data[rows]
    h_rows = history._data[rows] + g * g
    history._rebind(history._data.at[rows].set(h_rows))
    step = lr * g / (jnp.sqrt(h_rows) + epsilon)
    weight._rebind(weight._data.at[rows].add(-step))
    return weight


# --------------------------------------------------------------------------
# row-sparse gradient plumbing: merge (grad accumulation / kvstore
# aggregation) and jit-compiled lazy optimizer kernels at nnz cost.
# Parity: sparse gradient aggregation (src/kvstore/comm.h:104 CommCPU
# ReduceRowSparse) and the row_sparse optimizer kernels
# (src/operator/optimizer_op.cc:299,509,649,858 storage dispatch).
# --------------------------------------------------------------------------

def coalesce_rows(indices, values):
    """Host-side duplicate-row coalescing: sort row ids and segment-sum
    their values so each id appears ONCE, in ascending order.  This is
    the deterministic pre-pass both ends of the sparse push wire use —
    a batch with repeated ids must not depend on optimizer dispatch
    order (a momentum/adagrad state row updated twice in one push is
    order-sensitive; summed-once it is not).  Pure numpy: it runs on PS
    handler threads and the client push path without touching jax.

    Returns ``(unique_sorted_indices, summed_values)``."""
    import numpy as _onp
    idx = _onp.asarray(indices)
    val = _onp.asarray(values)
    if idx.ndim != 1 or val.shape[:1] != idx.shape:
        raise MXNetError(
            f"coalesce_rows: indices {idx.shape} / values {val.shape} "
            "mismatch (want indices (nnz,), values (nnz, ...))")
    if idx.size == 0:
        return idx, val
    uniq, inv = _onp.unique(idx, return_inverse=True)
    if uniq.size == idx.size:
        # duplicate-free: just establish sorted order (unique already
        # gave us the sort; reindex values to match)
        order = _onp.argsort(idx, kind="stable")
        return idx[order], val[order]
    out = _onp.zeros((uniq.size,) + val.shape[1:], dtype=val.dtype)
    _onp.add.at(out, inv, val)
    return uniq, out


def merge(a: RowSparseNDArray, b: RowSparseNDArray) -> RowSparseNDArray:
    """Sum two row_sparse arrays at O(nnz log nnz) cost, never
    materializing the dense shape (gradient accumulation / multi-device
    reduce)."""
    if tuple(a.shape) != tuple(b.shape):
        raise MXNetError(
            f"row_sparse merge: shape mismatch {a.shape} vs {b.shape}")
    rows = jnp.concatenate([a.indices, b.indices])
    vals = jnp.concatenate([a.data, b.data])
    uniq = jnp.unique(rows)                       # eager: nnz is data-dep
    inv = jnp.searchsorted(uniq, rows)
    summed = jax.ops.segment_sum(vals, inv, num_segments=int(uniq.shape[0]))
    return RowSparseNDArray(summed, uniq, a.shape)


def reduce_list(values) -> RowSparseNDArray:
    """Reduce a list of row_sparse values (kvstore multi-device push)."""
    acc = values[0]
    for v in values[1:]:
        acc = merge(acc, v)
    return acc


# jit cache for the lazy update kernels: ONE jax.jit wrapper per
# (kind, static hyperparams); jax's own signature cache compiles per
# (vocab, dim, nnz) shape as batches with new nnz appear.  Cost is
# O(nnz*dim) compute; no dense gradient is ever built.
_LAZY_JITS: dict = {}


def _lazy_kernel(kind: str, statics: tuple):
    key = (kind, statics)
    fn = _LAZY_JITS.get(key)
    if fn is not None:
        return fn
    st = dict(statics)
    rescale = st.get("rescale_grad", 1.0)
    clip = st.get("clip_gradient", -1.0)

    def prep(g, w_rows, wd):
        g = g * rescale
        if clip > 0:
            g = jnp.clip(g, -clip, clip)
        return g + wd * w_rows

    if kind == "sgd_update":
        def raw(lr, wd, w, vals, rows):
            w_rows = w[rows]
            g = prep(vals, w_rows, wd)
            return (w.at[rows].set(w_rows - lr * g),)
    elif kind == "sgd_mom_update":
        mom_c = st.get("momentum", 0.0)

        def raw(lr, wd, w, vals, rows, mom):
            w_rows = w[rows]
            g = prep(vals, w_rows, wd)
            m_rows = mom_c * mom[rows] - lr * g
            return (w.at[rows].add(m_rows), mom.at[rows].set(m_rows))
    elif kind == "adagrad_update":
        eps = st.get("epsilon", 1e-7)

        def raw(lr, wd, w, vals, rows, hist):
            w_rows = w[rows]
            g = prep(vals, w_rows, wd)
            h_rows = hist[rows] + g * g
            step = lr * g / (jnp.sqrt(h_rows) + eps)
            return (w.at[rows].add(-step), hist.at[rows].set(h_rows))
    elif kind == "adam_update":
        b1 = st.get("beta1", 0.9)
        b2 = st.get("beta2", 0.999)
        eps = st.get("epsilon", 1e-8)

        # bias correction is folded into lr by the CALLER (host-side,
        # like the dense Adam path) so the step count isn't a static
        # that would recompile the kernel every iteration
        def raw(lr, wd, w, vals, rows, mean, var):
            w_rows = w[rows]
            g = prep(vals, w_rows, wd)
            m_rows = b1 * mean[rows] + (1 - b1) * g
            v_rows = b2 * var[rows] + (1 - b2) * g * g
            step = lr * m_rows / (jnp.sqrt(v_rows) + eps)
            return (w.at[rows].add(-step), mean.at[rows].set(m_rows),
                    var.at[rows].set(v_rows))
    else:
        raise MXNetError(f"no row_sparse kernel for {kind!r}")

    # NO buffer donation: the weight array may be saved on the autograd
    # tape (the Embedding forward's record) — donating it would
    # invalidate a later backward replay.  Matches the dense
    # _jitted_update convention.
    fn = jax.jit(raw)
    _LAZY_JITS[key] = fn
    return fn


_LAZY_SUPPORTED = {"sgd_update", "sgd_mom_update", "adagrad_update",
                   "adam_update"}


def lazy_apply(kind: str, lr: float, wd: float, weight: NDArray,
               grad: RowSparseNDArray, states, statics: dict):
    """Run one jitted lazy update touching only grad.indices rows.
    Mutates weight/state NDArrays by rebinding.  Returns False when the
    optimizer has no sparse kernel (caller densifies)."""
    if kind not in _LAZY_SUPPORTED:
        return False
    fn = _lazy_kernel(kind, tuple(sorted(statics.items())))
    outs = fn(jnp.float32(lr), jnp.float32(wd), weight._data, grad.data,
              grad.indices, *[s._data for s in states])
    weight._rebind(outs[0])
    for s, new in zip(states, outs[1:]):
        s._rebind(new)
    return True
