"""Legacy model checkpoint helpers.

Parity: python/mxnet/model.py:189-268 (save_checkpoint / load_params /
load_checkpoint): ``prefix-symbol.json`` + ``prefix-%04d.params`` files
with ``arg:``/``aux:`` key prefixes — the interchange format most
pre-gluon MXNet code and tutorials rely on.
"""
from __future__ import annotations

import logging

from . import ndarray as nd

__all__ = ["save_checkpoint", "load_params", "load_checkpoint"]


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Write ``prefix-symbol.json`` + ``prefix-%04d.params`` (parity:
    model.py:189)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    nd.save(param_name, save_dict)
    logging.info('Saved checkpoint to "%s"', param_name)


def load_params(prefix, epoch):
    """Split a params file back into (arg_params, aux_params) (parity:
    model.py:221)."""
    save_dict = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in (save_dict or {}).items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) (parity: model.py:238)."""
    from . import symbol as sym
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
