"""LibSVM-format sparse data iterator.

Parity: src/io/iter_libsvm.cc (LibSVMIter): parses ``label
[idx:val ...]`` text into CSR batches.  The TPU build keeps batches as
CSRNDArray on the host — sparse is an eager/storage format here (see
ndarray/sparse.py); models densify or use sparse dot at the point of
use.  The reference's sparse prefetcher (iter_sparse_prefetcher.h) has
no analogue because the whole file is parsed into memory up front —
batch slicing is O(view), so there is nothing to prefetch.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as onp

from ..base import MXNetError
from ..ndarray import NDArray
from ..ndarray.sparse import CSRNDArray
from .io import DataBatch, DataDesc, DataIter

__all__ = ["LibSVMIter"]


def _parse_libsvm(path: str, indptr, indices, values, labels,
                  label_width: int):
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            head, feats = [], []
            for tok in parts:
                (feats if ":" in tok else head).append(tok)
            if len(head) < label_width:
                raise MXNetError(
                    f"libsvm line has {len(head)} labels, expected "
                    f">= {label_width}: {line[:60]!r}")
            labels.append([float(x) for x in head[:label_width]])
            for tok in feats:
                idx, val = tok.split(":", 1)
                indices.append(int(idx))
                values.append(float(val))
            indptr.append(len(indices))


class LibSVMIter(DataIter):
    """Iterator over libsvm text data yielding CSR batches.

    ``data_libsvm``: path to the data file; ``data_shape``: feature
    dimension (int or 1-tuple); optional ``label_libsvm``/``label_shape``
    stream multi-dimensional labels from a second file (parity:
    iter_libsvm.cc param struct).

    ``last_batch_handle`` makes the trailing-partial-batch policy
    explicit:

    * ``'pad'`` — the DEFAULT: the final batch wraps around to the
      epoch head to fill up (``DataBatch.pad`` tells the consumer how
      many trailing rows are refill, exactly the reference's
      round_batch semantics), so every batch has full ``batch_size``
      and no row is silently lost;
    * ``'discard'`` — the trailing partial batch is DROPPED; the
      dropped row count ticks the ``io.libsvm.discarded_rows``
      telemetry counter every epoch, so the loss is visible instead of
      silent.

    Legacy ``round_batch=False`` (with no ``last_batch_handle``) keeps
    its historical behavior of yielding the short final batch as-is.
    """

    def __init__(self, data_libsvm: str, data_shape, batch_size: int,
                 label_libsvm: Optional[str] = None, label_shape=None,
                 round_batch: bool = True,
                 last_batch_handle: Optional[str] = None, **kwargs):
        super().__init__(batch_size)
        if last_batch_handle not in (None, "pad", "discard"):
            raise MXNetError(
                f"last_batch_handle must be 'pad' or 'discard', got "
                f"{last_batch_handle!r}")
        self.last_batch_handle = last_batch_handle or \
            ("pad" if round_batch else "partial")
        if isinstance(data_shape, (tuple, list)):
            data_shape = int(data_shape[0])
        self.data_shape = int(data_shape)
        indptr, indices, values, labels = [0], [], [], []
        _parse_libsvm(data_libsvm, indptr, indices, values, labels, 1)
        if not labels:
            raise MXNetError(f"libsvm: no data rows in {data_libsvm!r}")
        if label_libsvm is not None:
            if isinstance(label_shape, (tuple, list)):
                label_shape = int(label_shape[0])
            with open(label_libsvm) as f:
                rows = [ln.strip() for ln in f if ln.strip()]
            lab = onp.zeros((len(rows), int(label_shape or 1)), onp.float32)
            for r, line in enumerate(rows):
                for tok in line.split():
                    if ":" in tok:
                        idx, val = tok.split(":", 1)
                        lab[r, int(idx)] = float(val)
                    else:
                        lab[r, 0] = float(tok)
            self._labels = lab
        else:
            self._labels = onp.asarray(labels, onp.float32)[:, 0]
        self._indptr = onp.asarray(indptr, onp.int64)
        self._indices = onp.asarray(indices, onp.int32)
        self._values = onp.asarray(values, onp.float32)
        self.num_rows = len(self._indptr) - 1
        if self._labels.shape[0] != self.num_rows:
            raise MXNetError(
                f"libsvm: {self.num_rows} data rows but "
                f"{self._labels.shape[0]} labels")
        if self._indices.size and \
                int(self._indices.max()) >= self.data_shape:
            raise MXNetError(
                f"libsvm: feature index {int(self._indices.max())} out of "
                f"range for data_shape {self.data_shape}")
        self.round_batch = round_batch
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self.data_shape))]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self._labels.ndim == 1 else \
            (self.batch_size, self._labels.shape[1])
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self.cur = 0

    def _slice(self, start: int, stop: int) -> CSRNDArray:
        lo, hi = self._indptr[start], self._indptr[stop]
        return CSRNDArray(self._values[lo:hi], self._indices[lo:hi],
                          self._indptr[start:stop + 1] - lo,
                          (stop - start, self.data_shape))

    def next(self) -> DataBatch:
        if self.cur >= self.num_rows:
            raise StopIteration
        stop = min(self.cur + self.batch_size, self.num_rows)
        pad = self.batch_size - (stop - self.cur)
        if pad and self.last_batch_handle == "discard":
            # drop the trailing partial batch — visibly: the discarded
            # row count is telemetry, not silence
            from .. import telemetry
            telemetry.counter("io.libsvm.discarded_rows").inc(
                stop - self.cur)
            self.cur = stop
            raise StopIteration
        if pad and self.last_batch_handle == "pad":
            # wrap around to fill the final batch (parity: round_batch)
            head = self._slice(self.cur, stop)
            tail = self._slice(0, pad)
            data = onp.vstack([head.todense().asnumpy(),
                               tail.todense().asnumpy()])
            from ..ndarray.sparse import array as sparse_array
            batch_data = sparse_array(data, stype="csr")
            label = onp.concatenate([self._labels[self.cur:stop],
                                     self._labels[:pad]])
        else:
            batch_data = self._slice(self.cur, stop)
            label = self._labels[self.cur:stop]
        self.cur = stop
        return DataBatch(data=[batch_data], label=[NDArray(label)], pad=pad)
