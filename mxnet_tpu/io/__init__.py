"""mx.io — legacy DataIter API.

Parity: python/mxnet/io/io.py (DataIter :179, NDArrayIter :490,
MXDataIter :799) + DataBatch/DataDesc.
"""
from .io import (DataIter, DataBatch, DataDesc, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter, MNISTIter)
from . import native
from .native import (ImageRecordIter, ImageRecordUInt8Iter,
                     ImageRecordInt8Iter)
from .libsvm import LibSVMIter

__all__ = ["DataIter", "DataBatch", "DataDesc", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter", "ImageRecordInt8Iter", "native",
           "LibSVMIter"]


def ImageDetRecordIter(path_imgrec, batch_size, data_shape, shuffle=False,
                       aug_list=None, **kwargs):
    """Detection record iterator (parity: the C++ ImageDetRecordIter,
    src/io/iter_image_det_recordio.cc): thin factory over
    image.ImageDetIter reading packed detection records — augmenter
    kwargs flow to CreateDetAugmenter inside ImageDetIter when no
    explicit aug_list is given."""
    from ..image.detection import ImageDetIter
    return ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                        path_imgrec=path_imgrec, shuffle=shuffle,
                        aug_list=aug_list, **kwargs)


__all__.append("ImageDetRecordIter")
