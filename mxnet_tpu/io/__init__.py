"""mx.io — legacy DataIter API.

Parity: python/mxnet/io/io.py (DataIter :179, NDArrayIter :490,
MXDataIter :799) + DataBatch/DataDesc.
"""
from .io import (DataIter, DataBatch, DataDesc, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter, MNISTIter)
from . import native
from .native import (ImageRecordIter, ImageRecordUInt8Iter,
                     ImageRecordInt8Iter)
from .libsvm import LibSVMIter

__all__ = ["DataIter", "DataBatch", "DataDesc", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter", "ImageRecordInt8Iter", "native",
           "LibSVMIter"]
