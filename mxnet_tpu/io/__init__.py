"""mx.io — legacy DataIter API.

Parity: python/mxnet/io/io.py (DataIter :179, NDArrayIter :490,
MXDataIter :799) + DataBatch/DataDesc.
"""
from .io import (DataIter, DataBatch, DataDesc, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter)
from . import native
from .native import ImageRecordIter
from .libsvm import LibSVMIter

__all__ = ["DataIter", "DataBatch", "DataDesc", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "ImageRecordIter", "native",
           "LibSVMIter"]
