"""mx.io — legacy DataIter API.

Parity: python/mxnet/io/io.py (DataIter :179, NDArrayIter :490,
MXDataIter :799) + DataBatch/DataDesc.
"""
from .io import (DataIter, DataBatch, DataDesc, NDArrayIter, CSVIter,
                 ResizeIter, PrefetchingIter, MNISTIter)
from . import native
from .native import (ImageRecordIter, ImageRecordUInt8Iter,
                     ImageRecordInt8Iter)
from .libsvm import LibSVMIter

__all__ = ["DataIter", "DataBatch", "DataDesc", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MNISTIter", "ImageRecordIter",
           "ImageRecordUInt8Iter", "ImageRecordInt8Iter", "native",
           "LibSVMIter"]


def ImageDetRecordIter(path_imgrec, batch_size, data_shape, shuffle=False,
                       aug_list=None, **kwargs):
    """Detection record iterator (parity: the C++ ImageDetRecordIter,
    src/io/iter_image_det_recordio.cc): thin factory over
    image.ImageDetIter reading packed detection records; augmenter
    kwargs go through CreateDetAugmenter."""
    from ..image.detection import CreateDetAugmenter, ImageDetIter
    if aug_list is None and kwargs:
        aug_keys = ("resize", "rand_crop", "rand_pad", "rand_mirror",
                    "mean", "std", "brightness", "contrast", "saturation",
                    "pca_noise", "hue", "inter_method", "min_object_covered",
                    "aspect_ratio_range", "area_range", "min_eject_coverage",
                    "max_attempts", "pad_val")
        aug_kwargs = {k: v for k, v in kwargs.items() if k in aug_keys}
        if aug_kwargs:
            aug_list = CreateDetAugmenter(data_shape, **aug_kwargs)
        kwargs = {k: v for k, v in kwargs.items() if k not in aug_keys}
    return ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                        path_imgrec=path_imgrec, shuffle=shuffle,
                        aug_list=aug_list, **kwargs)


__all__.append("ImageDetRecordIter")
