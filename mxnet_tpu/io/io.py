"""Legacy data iterators.

Parity: python/mxnet/io/io.py — DataIter protocol (provide_data/
provide_label, next/reset), NDArrayIter with shuffle + last-batch
handling, CSVIter, prefetching wrapper over the same protocol the C++
iterator chain implements (src/io/iter_prefetcher.h).
"""
from __future__ import annotations

import threading
import queue as _queue
from collections import namedtuple
from typing import Any, List, Optional

import numpy as onp

from ..base import MXNetError
from ..ndarray import NDArray

__all__ = ["DataIter", "DataBatch", "DataDesc", "NDArrayIter", "CSVIter",
           "ResizeIter", "PrefetchingIter", "MNISTIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    def __new__(cls, name, shape, dtype=onp.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Parity: io.py DataIter."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        if self.iter_next():
            return DataBatch(self.getdata(), self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


def _init_data(data, allow_empty, default_name):
    if data is None:
        return []
    if isinstance(data, (onp.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        data = {f"{default_name}{'_' + str(i) if i else ''}": d
                for i, d in enumerate(data)}
    out = []
    for k, v in data.items():
        if isinstance(v, NDArray):
            v = v.asnumpy()
        out.append((k, onp.asarray(v)))
    return out


class NDArrayIter(DataIter):
    """Parity: io.py NDArrayIter:490 (shuffle, pad/discard/roll_over)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0] if self.data else 0
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.cursor = -batch_size
        self._idx = onp.arange(self.num_data)
        if shuffle:
            onp.random.shuffle(self._idx)

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            onp.random.shuffle(self._idx)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _take(self, source):
        end = self.cursor + self.batch_size
        if end <= self.num_data:
            sel = self._idx[self.cursor:end]
        else:  # pad by wrapping around
            pad = end - self.num_data
            sel = onp.concatenate([self._idx[self.cursor:], self._idx[:pad]])
        return [NDArray(v[sel]) for _, v in source]

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        return max(0, end - self.num_data)


class CSVIter(DataIter):
    """Parity: the C++ CSVIter (src/io/iter_csv.cc) — host-side here."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=None,
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = onp.loadtxt(data_csv, delimiter=",", dtype=onp.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = onp.loadtxt(label_csv, delimiter=",", dtype=onp.float32)
            if label_shape:
                label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="pad")

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (parity: io.py
    ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def next(self):
        if self.cur == self.size:
            raise StopIteration
        try:
            batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            batch = self.data_iter.next()
        self.cur += 1
        return batch

    def iter_next(self):
        return self.cur < self.size


class PrefetchingIter(DataIter):
    """Prefetcher scheduled on the native C++ dependency engine (parity:
    io.py PrefetchingIter over the C++ threaded prefetcher,
    src/io/iter_prefetcher.h): fetch tasks are engine ops serialized by a
    mutable variable (exclusive access to the base iterator, ordered),
    running on the engine's worker pool.  Falls back to a Python thread
    if the native engine cannot load."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, list):
            iters = [iters]
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter here supports one base iter")
        self.iter = iters[0]
        super().__init__(self.iter.batch_size)
        self._depth = prefetch_depth
        self._queue: _queue.Queue = _queue.Queue(maxsize=prefetch_depth)
        self._thread = None
        self._stop = threading.Event()
        try:
            from ..engine import native_engine
            self._engine = native_engine()
            self._iter_var = self._engine.new_var()
        except Exception:
            self._engine = None
        self._start()

    def _fetch_one(self):
        if self._stop.is_set() or self._done:
            return
        try:
            batch = self.iter.next()
        except StopIteration:
            self._done = True
            self._queue.put(None)
            return
        self._queue.put(batch)

    def _start(self):
        self._done = False
        if self._engine is not None:
            for _ in range(self._depth):
                self._engine.push(self._fetch_one,
                                  mutable_vars=[self._iter_var])
            return

        def run():
            while not self._stop.is_set():
                try:
                    batch = self.iter.next()
                except StopIteration:
                    self._queue.put(None)
                    return
                self._queue.put(batch)
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def reset(self):
        self._stop.set()
        if self._engine is not None:
            # drain so in-flight fetch tasks can't block on a full queue
            while True:
                try:
                    self._queue.get_nowait()
                except _queue.Empty:
                    if self._engine is not None:
                        self._engine.wait_for_var(self._iter_var)
                    try:
                        self._queue.get_nowait()
                        continue
                    except _queue.Empty:
                        break
        else:
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
            if self._thread is not None:
                self._thread.join(timeout=5)
        self._stop.clear()
        self.iter.reset()
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        if self._engine is not None and not self._done:
            # refill: one consumed → schedule one more fetch
            self._engine.push(self._fetch_one, mutable_vars=[self._iter_var])
        return batch

    def iter_next(self):
        return True


class MNISTIter(DataIter):
    """Iterator over the original MNIST idx files (parity:
    src/io/iter_mnist.cc MNISTIter): reads idx3-ubyte images +
    idx1-ubyte labels, optional shuffle/flat/silent, scales pixels
    to [0,1] like the reference.
    """

    def __init__(self, image, label, batch_size=128, shuffle=False,
                 flat=False, silent=False, seed=0, part_index=0,
                 num_parts=1, **kwargs):
        super().__init__(batch_size)
        import gzip
        import struct

        def opener(path):
            return gzip.open(path, "rb") if path.endswith(".gz") \
                else open(path, "rb")

        with opener(image) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise MXNetError(f"{image} is not an idx3-ubyte file")
            X = onp.frombuffer(f.read(n * rows * cols), onp.uint8)
            X = X.reshape(n, rows, cols).astype("float32") / 255.0
        with opener(label) as f:
            magic, n2 = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise MXNetError(f"{label} is not an idx1-ubyte file")
            Y = onp.frombuffer(f.read(n2), onp.uint8).astype("float32")
        if n != n2:
            raise MXNetError("image/label counts differ")
        # multi-part reading (parity: part_index/num_parts fields)
        X = X[part_index::num_parts]
        Y = Y[part_index::num_parts]
        if shuffle:
            perm = onp.random.RandomState(seed).permutation(len(X))
            X, Y = X[perm], Y[perm]
        X = X.reshape(len(X), -1) if flat else X[:, None, :, :]
        if not silent:
            print(f"MNISTIter: load {len(X)} images, shuffle={shuffle}, "
                  f"flat={flat}")
        self._X, self._Y = X, Y
        self._cursor = 0

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self._X.shape[1:])]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self._cursor = 0

    def next(self):
        if self._cursor + self.batch_size > len(self._X):
            raise StopIteration
        i = self._cursor
        self._cursor += self.batch_size
        return DataBatch(
            data=[NDArray(self._X[i:i + self.batch_size])],
            label=[NDArray(self._Y[i:i + self.batch_size])], pad=0,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
