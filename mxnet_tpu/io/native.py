"""ctypes bindings for the native IO runtime (libmxtpu_io.so).

Parity: the reference's native data layer — dmlc recordio + the
threaded ImageRecordIter pipeline (src/io/iter_image_recordio_2.cc:887)
— implemented in C++ (src_native/) and consumed here the way the
reference's Python consumes libmxnet via ctypes (python/mxnet/base.py).

The library is built lazily (`make -C src_native`) on first use when a
toolchain is present; callers should catch MXNetError and fall back to
the pure-Python recordio path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as onp

from ..base import MXNetError

_LIB: Optional[ctypes.CDLL] = None
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "mxnet_tpu", "lib", "libmxtpu_io.so")
_SRC_DIR = os.path.join(_REPO_ROOT, "src_native")


def _build():
    if not os.path.isdir(_SRC_DIR):
        raise MXNetError("native IO sources not found")
    try:
        subprocess.run(["make", "-C", _SRC_DIR], check=True,
                       capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired) as e:
        raise MXNetError(f"building libmxtpu_io failed: {e}") from e


def get_lib() -> ctypes.CDLL:
    """Load (building if needed) the native IO library."""
    global _LIB
    if _LIB is not None:
        return _LIB
    if not os.path.exists(_LIB_PATH):
        _build()
    lib = ctypes.CDLL(_LIB_PATH)
    # writer
    lib.mxtpu_rec_writer_open.restype = ctypes.c_void_p
    lib.mxtpu_rec_writer_open.argtypes = [ctypes.c_char_p]
    lib.mxtpu_rec_writer_write.restype = ctypes.c_int64
    lib.mxtpu_rec_writer_write.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    lib.mxtpu_rec_writer_close.argtypes = [ctypes.c_void_p]
    # reader
    lib.mxtpu_rec_reader_open.restype = ctypes.c_void_p
    lib.mxtpu_rec_reader_open.argtypes = [ctypes.c_char_p]
    lib.mxtpu_rec_reader_next.restype = ctypes.c_int
    lib.mxtpu_rec_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_int64)]
    lib.mxtpu_rec_reader_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.mxtpu_rec_reader_tell.restype = ctypes.c_int64
    lib.mxtpu_rec_reader_tell.argtypes = [ctypes.c_void_p]
    lib.mxtpu_rec_reader_close.argtypes = [ctypes.c_void_p]
    # pipeline
    lib.mxtpu_pipe_create.restype = ctypes.c_void_p
    lib.mxtpu_pipe_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_uint64, ctypes.c_int,
        ctypes.c_int]
    lib.mxtpu_pipe_num_records.restype = ctypes.c_int64
    lib.mxtpu_pipe_num_records.argtypes = [ctypes.c_void_p]
    lib.mxtpu_pipe_next.restype = ctypes.c_int
    lib.mxtpu_pipe_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_float),
                                    ctypes.POINTER(ctypes.c_float)]
    lib.mxtpu_pipe_reset.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.mxtpu_pipe_destroy.argtypes = [ctypes.c_void_p]
    _LIB = lib
    return lib


def available() -> bool:
    try:
        get_lib()
        return True
    except (MXNetError, OSError):
        return False


class NativeRecordWriter:
    """Sequential dmlc-format record writer (native)."""

    def __init__(self, path: str):
        self._lib = get_lib()
        self._h = self._lib.mxtpu_rec_writer_open(path.encode())
        if not self._h:
            raise MXNetError(f"cannot open {path} for writing")

    def write(self, buf: bytes) -> int:
        arr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
        pos = self._lib.mxtpu_rec_writer_write(self._h, arr, len(buf))
        if pos < 0:
            raise MXNetError("record write failed")
        return pos

    def close(self):
        if self._h:
            self._lib.mxtpu_rec_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NativeRecordReader:
    """Sequential dmlc-format record reader (native)."""

    def __init__(self, path: str):
        self._lib = get_lib()
        self._h = self._lib.mxtpu_rec_reader_open(path.encode())
        if not self._h:
            raise MXNetError(f"cannot open {path}")

    def read(self) -> Optional[bytes]:
        out = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_int64(0)
        status = self._lib.mxtpu_rec_reader_next(
            self._h, ctypes.byref(out), ctypes.byref(length))
        if status == 0:
            return None
        if status < 0:
            raise MXNetError(f"corrupt record stream (code {status})")
        return ctypes.string_at(out, length.value) if length.value else b""

    def seek(self, offset: int):
        if self._lib.mxtpu_rec_reader_seek(self._h, offset) != 0:
            raise MXNetError("seek failed")

    def tell(self) -> int:
        return self._lib.mxtpu_rec_reader_tell(self._h)

    def close(self):
        if self._h:
            self._lib.mxtpu_rec_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ImageRecordIter:
    """Threaded native image pipeline (parity: ImageRecordIter,
    src/io/iter_image_recordio_2.cc:887-940).

    Yields DataBatch with NCHW float32 data, like the reference (the
    native pipeline fills NHWC — TPU's preferred layout — and this
    wrapper transposes unless ``layout="NHWC"``).
    """

    def __init__(self, path_imgrec: str, batch_size: int,
                 data_shape=(3, 224, 224), label_width: int = 1,
                 shuffle: bool = False, rand_mirror: bool = False,
                 rand_crop: bool = False, mean_r: float = 0.0,
                 mean_g: float = 0.0, mean_b: float = 0.0,
                 std_r: float = 1.0, std_g: float = 1.0, std_b: float = 1.0,
                 seed: int = 0, preprocess_threads: int = 4,
                 prefetch_buffer: int = 4, layout: str = "NCHW",
                 round_batch: bool = True, **kwargs):
        self._lib = get_lib()
        c, h, w = data_shape
        mean = (ctypes.c_float * 3)(mean_r, mean_g, mean_b)
        std = (ctypes.c_float * 3)(std_r, std_g, std_b)
        self._h = self._lib.mxtpu_pipe_create(
            path_imgrec.encode(), batch_size, h, w, c, label_width,
            int(shuffle), int(rand_mirror), int(rand_crop), mean, std,
            seed, preprocess_threads, prefetch_buffer)
        if not self._h:
            raise MXNetError(f"cannot open record file {path_imgrec}")
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width
        self.layout = layout
        self._threads = preprocess_threads
        self._data_buf = onp.empty((batch_size, h, w, c), onp.float32)
        self._label_buf = onp.empty((batch_size, label_width), onp.float32)

    @property
    def num_records(self) -> int:
        return int(self._lib.mxtpu_pipe_num_records(self._h))

    def __iter__(self):
        return self

    def __next__(self):
        from .io import DataBatch
        from ..ndarray import NDArray
        n = self._lib.mxtpu_pipe_next(
            self._h,
            self._data_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._label_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n <= 0:
            raise StopIteration
        data = self._data_buf
        if self.layout == "NCHW":
            data = onp.transpose(data, (0, 3, 1, 2))
        label = self._label_buf[:, 0] if self.label_width == 1 \
            else self._label_buf
        return DataBatch(data=[NDArray(data.copy())],
                         label=[NDArray(label.copy())],
                         pad=self.batch_size - n)

    def next(self):
        return self.__next__()

    def reset(self):
        self._lib.mxtpu_pipe_reset(self._h, self._threads)

    def close(self):
        if getattr(self, "_h", None):
            self._lib.mxtpu_pipe_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ImageRecordUInt8Iter(ImageRecordIter):
    """ImageRecordIter yielding raw uint8 pixels (parity:
    ImageRecordUInt8Iter, iter_image_recordio_2.cc:908): no
    mean/std normalization, data dtype uint8 — the int8/uint8
    quantized-inference input path."""

    _out_dtype = onp.uint8
    _offset = 0

    def __init__(self, *args, **kwargs):
        for k in ("mean_r", "mean_g", "mean_b"):
            kwargs.pop(k, None)
        for k in ("std_r", "std_g", "std_b"):
            kwargs.pop(k, None)
        super().__init__(*args, **kwargs)

    def __next__(self):
        batch = super().__next__()
        from ..ndarray import NDArray
        batch.data = [NDArray((onp.clip(d.asnumpy(), 0, 255)
                               + self._offset).astype(self._out_dtype))
                      for d in batch.data]
        return batch


class ImageRecordInt8Iter(ImageRecordUInt8Iter):
    """Signed-int8 variant (parity: ImageRecordInt8Iter,
    iter_image_recordio_2.cc:925): pixels shifted into [-128, 127]."""

    _out_dtype = onp.int8
    _offset = -128
