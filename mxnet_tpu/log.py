"""Logging helpers.

Parity: python/mxnet/log.py — ``get_logger(name, filename, filemode,
level)`` with the reference's `%(asctime)s` head format and a
level-colored formatter when attached to a tty.
"""
from __future__ import annotations

import logging
import sys

__all__ = ["get_logger", "DEBUG", "INFO", "WARNING", "ERROR", "NOTSET"]

DEBUG = logging.DEBUG
INFO = logging.INFO
WARNING = logging.WARNING
ERROR = logging.ERROR
NOTSET = logging.NOTSET

_HEAD = "%(asctime)-15s %(message)s"


class _ColorFormatter(logging.Formatter):
    _COLORS = {logging.WARNING: "\x1b[0;33m", logging.ERROR: "\x1b[0;31m"}

    def format(self, record):
        msg = super().format(record)
        color = self._COLORS.get(record.levelno)
        return f"{color}{msg}\x1b[0m" if color else msg


def get_logger(name=None, filename=None, filemode=None,
               level=WARNING) -> logging.Logger:
    """Parity: log.py get_logger."""
    logger = logging.getLogger(name)
    if getattr(logger, "_mxnet_tpu_init", False):
        return logger
    if filename:
        handler = logging.FileHandler(filename, filemode or "a")
        handler.setFormatter(logging.Formatter(_HEAD))
    else:
        handler = logging.StreamHandler(sys.stderr)
        fmt = (_ColorFormatter(_HEAD)
               if getattr(sys.stderr, "isatty", lambda: False)()
               else logging.Formatter(_HEAD))
        handler.setFormatter(fmt)
    logger.addHandler(handler)
    logger.setLevel(level)
    logger._mxnet_tpu_init = True
    return logger
