"""AMP execution policy: per-op compute dtype, traced INTO executables.

The legacy ``amp.init`` monkeypatched ``op.fn`` — a mutation the
compiled hot paths can't see (fused_step and cached_step replay cached
partials, so a wrapper installed after capture never runs) and one that
breaks the partial-identity caching the capture layer keys on.  The
policy replaces that: a process-global (enabled, compute-dtype) pair
that the op funnel consults when it BUILDS a bound partial
(ops/registry.bound_fn), so the casts are part of the traced function
itself and flow into every executable derived from it — the eager
per-op jit, the autograd vjp, the cached whole-step capture, the SPMD
scan, and the serving engine's bucket compiles.

Cache coherence is by key participation, not mutation:
:func:`cache_token` joins ``ops.registry._env_numerics_key()``, which
is a component of every partial/jit cache key, the fused-step family
key, the cached-step structure key, and the serving bucket key.
Flipping AMP on/off (or changing the dtype) therefore mints fresh
executables instead of corrupting cached ones.

Compute dtypes:

- ``bfloat16`` (default) — same exponent range as fp32, the TPU MXU's
  native low precision.
- ``float8_e4m3fn`` (``MXNET_AMP_DTYPE=float8_e4m3fn`` or ``fp8``) —
  inputs of matmul-class ops are quantized through e4m3 and the op
  computes in bf16 (quantize-dequantize emulation: e4m3 does not
  implicitly promote against f32, so letting raw fp8 arrays escape an
  op would poison every downstream elementwise op; the wire layers
  that explicitly want 1-byte payloads cast explicitly).

Category semantics (from :mod:`.lists`):

- TARGET_DTYPE_OPS: f32/f64 float inputs cast down to the compute
  dtype (storage dtype for fp8), output left in low precision.
- FP32_OPS: low-precision float inputs cast up to f32.
- WIDEST_TYPE_CASTS: all float inputs cast to the widest float dtype
  among them.
- unlisted ops: untouched.
"""
from __future__ import annotations

import os
from typing import Optional

from . import lists

__all__ = [
    "enabled", "activate", "deactivate", "compute_dtype",
    "compute_dtype_str", "storage_dtype", "compute_itemsize",
    "cache_token", "category", "wrap", "wire_cast", "kernel_key_dtype",
]

# explicit amp.init() activation; the MXNET_AMP env var activates
# without an init call (read per-token so tests can flip it)
_active = False
_active_dtype: Optional[str] = None   # dtype passed to activate()

_TARGET = frozenset(lists.TARGET_DTYPE_OPS)
_FP32 = frozenset(lists.FP32_OPS)
_WIDEST = frozenset(lists.WIDEST_TYPE_CASTS)

_DTYPE_ALIASES = {
    "bfloat16": "bfloat16", "bf16": "bfloat16",
    "float16": "float16", "fp16": "float16",
    "float8_e4m3fn": "float8_e4m3fn", "fp8": "float8_e4m3fn",
    "e4m3": "float8_e4m3fn",
}


def _canon(name) -> str:
    s = str(name).lower()
    try:
        return _DTYPE_ALIASES[s]
    except KeyError:
        raise ValueError(
            f"unsupported AMP compute dtype {name!r}; one of "
            f"{sorted(set(_DTYPE_ALIASES))}") from None


def activate(dtype=None) -> None:
    """Turn the policy on (amp.init calls this).  ``dtype`` overrides
    ``MXNET_AMP_DTYPE``; None defers to the env var / bf16 default."""
    global _active, _active_dtype
    _active = True
    _active_dtype = _canon(dtype) if dtype is not None else None


def deactivate() -> None:
    global _active, _active_dtype
    _active = False
    _active_dtype = None


def enabled() -> bool:
    """True when amp.init() ran or MXNET_AMP=1 is exported."""
    return _active or os.environ.get("MXNET_AMP") == "1"


def compute_dtype_str() -> str:
    """Canonical name of the active compute dtype (bf16 when off —
    callers should gate on :func:`enabled` first)."""
    if _active_dtype is not None:
        return _active_dtype
    env = os.environ.get("MXNET_AMP_DTYPE")
    return _canon(env) if env else "bfloat16"


def storage_dtype():
    """The dtype low-precision values are QUANTIZED through (e4m3 for
    fp8) — what the wire layers ship."""
    import jax.numpy as jnp
    s = compute_dtype_str()
    if s == "float8_e4m3fn":
        import ml_dtypes
        return jnp.dtype(ml_dtypes.float8_e4m3fn)
    return jnp.dtype(s)


def compute_dtype():
    """The dtype matmul-class ops COMPUTE in: bf16 for both the bf16
    and fp8 policies (fp8 is quantize-dequantize emulated), f16 for
    the float16 parity mode."""
    import jax.numpy as jnp
    s = compute_dtype_str()
    if s == "float8_e4m3fn":
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(s)


def compute_itemsize() -> int:
    """Bytes per element on the gradient wire under the policy (1 for
    fp8, 2 for bf16/f16, 4 when the policy is off)."""
    if not enabled():
        return 4
    return storage_dtype().itemsize


def cache_token():
    """Hashable policy fingerprint; joins every executable cache key
    via ``ops.registry._env_numerics_key()``.  None while off keeps
    pre-existing keys stable."""
    if not enabled():
        return None
    return ("amp", compute_dtype_str())


def category(op_name: str) -> Optional[str]:
    if op_name in _TARGET:
        return "target"
    if op_name in _FP32:
        return "fp32"
    if op_name in _WIDEST:
        return "widest"
    return None


def wire_cast(g):
    """The gradient-wire round-trip, traced: quantize ``g`` through the
    policy's storage dtype and dequantize back, so the collective GSPMD
    inserts next to it ships 1-byte (fp8) / 2-byte (bf16/f16) payloads
    while the consumer (optimizer master update) sees the dequantized
    value.  Identity for non-float inputs, for arrays already at or
    below the wire width, and while the policy is off — safe to leave
    in a traced step unconditionally.  Every mesh-axis wire (dp
    gradient legs, pp activation hops, ep dispatch payloads) funnels
    through this one cast discipline."""
    if not enabled():
        return g
    import jax.numpy as jnp
    if not _is_float(g):
        return g
    wire = storage_dtype()
    if g.dtype.itemsize <= wire.itemsize:
        return g
    return g.astype(wire).astype(g.dtype)


def kernel_key_dtype(dtype_str: str) -> str:
    """The dtype a kernel-registry cache key should carry for a call
    arriving as ``dtype_str``: under AMP an fp32 call site runs the
    kernel on policy-cast operands, so the key must name the compute
    dtype or a bf16 call after an fp32 tune resolves the fp32 winner
    (ISSUE 15 satellite fix)."""
    if enabled() and dtype_str in ("float32", "float64"):
        return str(compute_dtype())
    return dtype_str


def _is_float(a) -> bool:
    import jax.numpy as jnp
    dt = getattr(a, "dtype", None)
    if dt is None:
        return False
    try:
        return jnp.issubdtype(dt, jnp.floating)
    except TypeError:
        return False


def wrap(op_name: str, fn):
    """Return ``fn`` or a casting closure per the op's category.  The
    closure runs INSIDE the traced function, so the casts are baked
    into whichever executable captures it.  Must only be called while
    :func:`enabled` — the caller keys its cache on
    :func:`cache_token`, which is what invalidates stale wrappers."""
    cat = category(op_name)
    if cat is None:
        return fn
    import jax.numpy as jnp
    if cat == "target":
        sdt = storage_dtype()
        cdt = compute_dtype()
        wide = (jnp.float32, jnp.float64)

        def target_cast(a):
            if _is_float(a) and a.dtype in wide:
                a = a.astype(sdt)
                if sdt != cdt:       # fp8: quantize, compute in bf16
                    a = a.astype(cdt)
            return a

        def wrapped_target(*arrays, **params):
            return fn(*[target_cast(a) for a in arrays], **params)
        return wrapped_target
    if cat == "fp32":
        def wrapped_fp32(*arrays, **params):
            cast = [a.astype(jnp.float32)
                    if _is_float(a) and a.dtype != jnp.float64
                    and a.dtype != jnp.float32 else a
                    for a in arrays]
            return fn(*cast, **params)
        return wrapped_fp32

    def wrapped_widest(*arrays, **params):
        fdts = [a.dtype for a in arrays if _is_float(a)]
        if len(set(fdts)) > 1:
            widest = max(fdts, key=lambda d: (d.itemsize, str(d)))
            arrays = [a.astype(widest) if _is_float(a) else a
                      for a in arrays]
        return fn(*arrays, **params)
    return wrapped_widest
