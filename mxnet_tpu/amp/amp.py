"""AMP core.

Parity: python/mxnet/contrib/amp/amp.py (init :282, init_trainer :322,
convert_model :548, convert_hybrid_block :633).  ``init`` patches the op
registry so MXU-bound ops (conv/FC/matmul) compute in the target dtype
with amp_cast insertions at their inputs — the imperative analogue of the
reference's monkeypatching; graph-mode conversion casts parameters and
wraps the block.
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as onp
import jax.numpy as jnp

from ..base import MXNetError, np_dtype
from ..ops import registry as _reg
from . import lists
from .loss_scaler import LossScaler

_initialized = False
_target_dtype = None
_orig_fns = {}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP globally (parity: amp.init).

    Wraps the registered compute fn of every TARGET_DTYPE_OP so inputs are
    cast to ``target_dtype`` (amp_cast) and outputs stay in low precision;
    FP32_OPS get their inputs cast up.
    """
    global _initialized, _target_dtype
    if _initialized:
        return
    dt = np_dtype(target_dtype)
    _target_dtype = dt
    low_ops = list(target_precision_ops or lists.TARGET_DTYPE_OPS)
    fp32 = list(fp32_ops or lists.FP32_OPS)

    def wrap_low(fn):
        @functools.wraps(fn)
        def wrapped(*arrays, **params):
            cast = [a.astype(dt) if hasattr(a, "dtype")
                    and onp.dtype(a.dtype) == onp.float32 else a
                    for a in arrays]
            return fn(*cast, **params)
        return wrapped

    def wrap_fp32(fn):
        @functools.wraps(fn)
        def wrapped(*arrays, **params):
            cast = [a.astype(jnp.float32) if hasattr(a, "dtype")
                    and onp.dtype(a.dtype) == dt else a for a in arrays]
            return fn(*cast, **params)
        return wrapped

    for name in low_ops:
        try:
            op = _reg.get(name)
        except MXNetError:
            continue
        if name not in _orig_fns:
            _orig_fns[name] = op.fn
            op.fn = wrap_low(op.fn)
    for name in fp32:
        try:
            op = _reg.get(name)
        except MXNetError:
            continue
        if name not in _orig_fns:
            _orig_fns[name] = op.fn
            op.fn = wrap_fp32(op.fn)
    _initialized = True


def reset():
    """Undo init() (test helper; the reference has no un-init)."""
    global _initialized, _target_dtype
    for name, fn in _orig_fns.items():
        _reg.get(name).fn = fn
    _orig_fns.clear()
    _initialized = False
    _target_dtype = None


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a Trainer (parity: amp.init_trainer)."""
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    return trainer


class scale_loss:
    """``with amp.scale_loss(loss, trainer) as scaled:`` context."""

    def __init__(self, loss, trainer):
        self._trainer = trainer
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler is None:
            init_trainer(trainer)
            scaler = trainer._amp_loss_scaler
        self._scaler = scaler
        trainer._scale = trainer._amp_original_scale / scaler.loss_scale
        if isinstance(loss, (list, tuple)):
            self._scaled = [l * scaler.loss_scale for l in loss]
        else:
            self._scaled = loss * scaler.loss_scale

    def __enter__(self):
        return self._scaled

    def __exit__(self, *exc):
        scaler = self._scaler
        overflow = scaler.has_overflow(self._trainer._params)
        scaler.update_scale(overflow)
        if overflow:  # zero grads so the step is a no-op
            for p in self._trainer._params:
                if p._grad is not None:
                    p.zero_grad()
        return False


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    for p in trainer._params:
        if p._grad is not None:
            p._grad._rebind(p._grad._data / scaler.loss_scale)


def convert_model(net, target_dtype="bfloat16", cast_params=True):
    """Cast a model for low-precision inference (parity: convert_model)."""
    dt = np_dtype(target_dtype)
    if cast_params:
        for p in net.collect_params().values():
            if p._data is not None and p.dtype == onp.float32:
                p.cast(dt)
    return net


def convert_hybrid_block(block, target_dtype="bfloat16", cast_params=False):
    """Parity: amp.convert_hybrid_block — here the block is wrapped so
    inputs are cast to the target dtype and outputs back to fp32; the
    heavy lifting (keeping sensitive ops fp32) comes from the patched
    registry (init)."""
    init(target_dtype)
    if cast_params:
        convert_model(block, target_dtype)
    return block
