"""AMP core.

Parity: python/mxnet/contrib/amp/amp.py (init :282, init_trainer :322,
convert_model :548, convert_hybrid_block :633).  ``init`` activates the
execution policy (:mod:`.policy`) consulted by the op funnel when it
builds bound partials, so MXU-bound ops (conv/FC/matmul) compute in the
target dtype with the casts TRACED into every derived executable —
eager jit, autograd vjp, the cached whole-step capture, the SPMD scan
and serving buckets — instead of monkeypatched around eager calls.
Graph-mode conversion casts parameters and wraps the block.
"""
from __future__ import annotations

from typing import Optional

import numpy as onp

from ..base import np_dtype
from . import lists, policy
from .loss_scaler import LossScaler

_initialized = False
_target_dtype = None


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP globally (parity: amp.init).

    Activates the execution policy: every TARGET_DTYPE_OP's bound
    partial gets its f32 inputs cast to ``target_dtype`` at trace time
    (amp_cast), FP32_OPS get theirs cast up.  Custom op lists are not
    supported on the policy path — the lists are the single source the
    cache keys are derived from."""
    global _initialized, _target_dtype
    if _initialized:
        return
    _target_dtype = policy._canon(target_dtype)
    policy.activate(target_dtype)
    _initialized = True


def reset():
    """Undo init() (test helper; the reference has no un-init).  Cached
    executables traced under the policy are retired by cache-key
    participation, not mutation — nothing to restore here."""
    global _initialized, _target_dtype
    policy.deactivate()
    _initialized = False
    _target_dtype = None


def init_trainer(trainer):
    """Attach a dynamic LossScaler to a Trainer (parity: amp.init_trainer)."""
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    return trainer


class scale_loss:
    """``with amp.scale_loss(loss, trainer) as scaled:`` context."""

    def __init__(self, loss, trainer):
        self._trainer = trainer
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler is None:
            init_trainer(trainer)
            scaler = trainer._amp_loss_scaler
        self._scaler = scaler
        trainer._scale = trainer._amp_original_scale / scaler.loss_scale
        if isinstance(loss, (list, tuple)):
            self._scaled = [l * scaler.loss_scale for l in loss]
        else:
            self._scaled = loss * scaler.loss_scale

    def __enter__(self):
        return self._scaled

    def __exit__(self, *exc):
        scaler = self._scaler
        overflow = scaler.has_overflow(self._trainer._params)
        scaler.update_scale(overflow)
        if overflow:  # zero grads so the step is a no-op
            for p in self._trainer._params:
                if p._grad is not None:
                    p.zero_grad()
        return False


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    for p in trainer._params:
        if p._grad is not None:
            p._grad._rebind(p._grad._data / scaler.loss_scale)


def convert_model(net, target_dtype="bfloat16", cast_params=True):
    """Cast a model for low-precision inference (parity: convert_model)."""
    dt = np_dtype(target_dtype)
    if cast_params:
        for p in net.collect_params().values():
            if p._data is not None and p.dtype == onp.float32:
                p.cast(dt)
    return net


def convert_hybrid_block(block, target_dtype="bfloat16", cast_params=False):
    """Parity: amp.convert_hybrid_block — here the block is wrapped so
    inputs are cast to the target dtype and outputs back to fp32; the
    heavy lifting (keeping sensitive ops fp32) comes from the patched
    registry (init)."""
    init(target_dtype)
    if cast_params:
        convert_model(block, target_dtype)
    return block
