"""Automatic Mixed Precision.

Parity: python/mxnet/contrib/amp/ (amp.py init/init_trainer/convert_*,
loss_scaler.py, lists/symbol_fp16.py) over the amp_cast ops and
low_precision_pass.cc.  TPU-first: the target dtype is bfloat16 — same
exponent range as fp32, so loss scaling is a no-op by default — but the
full dynamic LossScaler is provided for float16 parity.
"""
from .amp import (init, init_trainer, reset, scale_loss, unscale,
                  convert_model, convert_hybrid_block)
from .loss_scaler import LossScaler, all_finite
from . import lists, policy

__all__ = ["init", "init_trainer", "reset", "scale_loss", "unscale",
           "convert_model", "convert_hybrid_block", "LossScaler",
           "all_finite", "lists", "policy"]
