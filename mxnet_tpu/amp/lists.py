"""AMP op lists.

Parity: python/mxnet/contrib/amp/lists/symbol_fp16.py / symbol_bf16.py —
which ops run in low precision (MXU-bound), which stay fp32
(numerically sensitive), which follow their inputs.
"""

# matmul/conv-class ops: always worth low precision on the MXU
FP16_FP32_FUNCS = TARGET_DTYPE_OPS = [
    "Convolution", "Deconvolution", "FullyConnected", "dot", "batch_dot",
    "matmul",
]

# numerically sensitive: keep fp32
FP32_FUNCS = FP32_OPS = [
    "softmax", "log_softmax", "softmax_cross_entropy", "SoftmaxOutput",
    "BatchNorm", "LayerNorm", "GroupNorm", "InstanceNorm", "LRN", "RMSNorm",
    "norm", "mean", "sum", "exp", "log", "erfinv", "CTCLoss",
]

# follow the widest input dtype
WIDEST_TYPE_CASTS = CONDITIONAL_FP32_FUNCS = [
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "concat", "stack", "where",
]

BF16 = "bfloat16"
FP16 = "float16"
