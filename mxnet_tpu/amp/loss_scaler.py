"""Dynamic loss scaler.

Parity: python/mxnet/contrib/amp/loss_scaler.py:26 — scale up every
`scale_window` clean steps, halve on overflow, skip the update that
overflowed.
"""
from __future__ import annotations

import numpy as onp

__all__ = ["LossScaler"]


class LossScaler:
    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        """Check grads for inf/nan (parity: LossScaler.has_overflow)."""
        import jax.numpy as jnp
        for p in params:
            g = getattr(p, "_grad", None)
            if g is None:
                continue
            if not bool(jnp.isfinite(g._data).all()):
                return True
        return False

    def update_scale(self, overflow: bool):
        if overflow:
            self.loss_scale = max(self.loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self.loss_scale *= self._scale_factor
                self._unskipped = 0
