"""Dynamic loss scaler.

Parity: python/mxnet/contrib/amp/loss_scaler.py:26 — scale up every
`scale_window` clean steps, halve on overflow, skip the update that
overflowed.

Two execution shapes:

- the eager path (``amp.scale_loss``) calls :meth:`has_overflow` /
  :meth:`update_scale` on the host.  ``has_overflow`` runs ONE jitted
  fused all-finite reduction over the whole gradient pytree (a single
  dispatch, one bool crossing the device boundary) instead of the old
  per-param ``isfinite().all()`` materialization; the legacy loop is
  kept behind ``MXNET_AMP_FUSED_OVERFLOW=0``.
- the captured funnels (cached_step, the SPMD scan) trace the scale
  arithmetic and the all-finite predicate INTO the step executable and
  hand the resulting device scalars back via :meth:`adopt_traced`,
  which defers the host read until someone actually looks at
  ``loss_scale`` — the hot path never blocks on the scaler.
"""
from __future__ import annotations

import os

__all__ = ["LossScaler", "all_finite"]

_FUSED_FN = None


def _fused_all_finite():
    """The jitted reduction, built lazily (jax import cost) and cached
    per gradient-pytree structure by jax.jit itself."""
    global _FUSED_FN
    if _FUSED_FN is None:
        import jax
        import jax.numpy as jnp

        def allfin(leaves):
            acc = jnp.bool_(True)
            for g in leaves:
                if jnp.issubdtype(g.dtype, jnp.floating):
                    acc = jnp.logical_and(acc, jnp.isfinite(g).all())
            return acc
        _FUSED_FN = jax.jit(allfin)
    return _FUSED_FN


def all_finite(leaves):
    """One fused device-side all-finite over a list of arrays; returns
    a 0-d device bool (callers decide when to sync)."""
    if not leaves:
        import jax.numpy as jnp
        return jnp.bool_(True)
    return _fused_all_finite()(list(leaves))


class LossScaler:
    def __init__(self, init_scale=2 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self._loss_scale = float(init_scale)
        self._scale_factor = scale_factor
        self._scale_window = scale_window
        self._unskipped = 0
        self._pending = None    # traced (scale, unskipped, skipped)

    # -- traced-state adoption (captured funnels) -----------------------

    def adopt_traced(self, scale, unskipped, skipped) -> None:
        """Adopt this step's traced scaler outputs (device scalars)
        without a host sync; the previous pending triple folds into
        host floats first (one step of lag, read off the critical
        path)."""
        self._fold()
        self._pending = (scale, unskipped, skipped)

    def _fold(self) -> None:
        p = self._pending
        if p is None:
            return
        self._pending = None
        skipped = int(p[2])      # bool for one step, a count for a
        self._loss_scale = float(p[0])   # fused scan window
        self._unskipped = int(p[1])
        self._note(skipped)

    def _note(self, skipped) -> None:
        from .. import telemetry
        n = int(skipped)
        if n:
            telemetry.counter("amp.overflow_steps").inc(n)
            telemetry.counter("amp.skipped_updates").inc(n)
        telemetry.gauge("amp.loss_scale").set(self._loss_scale)

    # -- host-visible state --------------------------------------------

    @property
    def loss_scale(self) -> float:
        self._fold()
        return self._loss_scale

    @loss_scale.setter
    def loss_scale(self, v) -> None:
        self._pending = None
        self._loss_scale = float(v)

    def state(self) -> dict:
        """JSON-able scaler state for checkpoint headers; restoring it
        resumes the dynamic schedule deterministically."""
        self._fold()
        return {"loss_scale": self._loss_scale,
                "unskipped": int(self._unskipped),
                "scale_factor": float(self._scale_factor),
                "scale_window": int(self._scale_window)}

    def load_state(self, d: dict) -> None:
        self._pending = None
        self._loss_scale = float(d["loss_scale"])
        self._unskipped = int(d.get("unskipped", 0))
        self._scale_factor = float(d.get("scale_factor",
                                         self._scale_factor))
        self._scale_window = int(d.get("scale_window",
                                       self._scale_window))

    # -- eager path -----------------------------------------------------

    def has_overflow(self, params) -> bool:
        """Check grads for inf/nan (parity: LossScaler.has_overflow).
        One fused jitted reduction by default; MXNET_AMP_FUSED_OVERFLOW=0
        restores the per-param host loop."""
        import jax.numpy as jnp
        from ..imperative.cached_step import ensure_real
        grads = []
        for p in params:
            g = getattr(p, "_grad", None)
            if g is None:
                continue
            # under a captured step the grad buffer may still be a
            # deferred placeholder: reading it here is a host sync,
            # which takes the documented graph-break path
            ensure_real(g)
            grads.append(g._data)
        if os.environ.get("MXNET_AMP_FUSED_OVERFLOW", "1") == "0":
            for g in grads:
                if not bool(jnp.isfinite(g).all()):
                    return True
            return False
        return not bool(all_finite(grads))

    def update_scale(self, overflow: bool):
        self._fold()
        if overflow:
            self._loss_scale = max(
                self._loss_scale / self._scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._scale_window:
                self._loss_scale *= self._scale_factor
                self._unskipped = 0
        self._note(overflow)
