"""mx.npx — numpy extensions (parity: python/mxnet/numpy_extension/ —
the `_npx_*` op namespace: nn ops with numpy arrays, sequence ops,
set_np/reset_np re-exports)."""
from __future__ import annotations

from ..util import (set_np, reset_np, is_np_array, is_np_shape,  # noqa: F401
                    use_np)
from ..context import (cpu, gpu, tpu, num_gpus, num_tpus,  # noqa: F401
                       current_context)
from ..ndarray.register import make_op_func as _make
from ..ops import registry as _reg

# nn/extension ops under their npx names (parity: _npx_* registrations)
_NPX_OPS = {
    "activation": "Activation",
    "batch_norm": "BatchNorm",
    "convolution": "Convolution",
    "deconvolution": "Deconvolution",
    "fully_connected": "FullyConnected",
    "pooling": "Pooling",
    "dropout": "Dropout",
    "embedding": "Embedding",
    "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm",
    "instance_norm": "InstanceNorm",
    "leaky_relu": "LeakyReLU",
    "softmax": "softmax",
    "log_softmax": "log_softmax",
    "masked_softmax": "masked_softmax",
    "masked_log_softmax": "masked_log_softmax",
    "topk": "topk",
    "pick": "pick",
    "one_hot": "one_hot",
    "rnn": "RNN",
    "batch_dot": "batch_dot",
    "sequence_mask": "SequenceMask",
    "smooth_l1": "smooth_l1",
    "gamma": "gamma",
    "reshape_like": None,
    "broadcast_like": "broadcast_like",
    "arange_like": "arange_like",
    "shape_array": "shape_array",
    "gather_nd": "gather_nd",
    "scatter_nd": "scatter_nd",
    "slice": "slice",
    "slice_axis": "slice_axis",
    "slice_like": "slice_like",
    "ctc_loss": "CTCLoss",
    "sigmoid": "sigmoid",
    "relu": "relu",
    "batch_flatten": "Flatten",
    "multibox_prior": "MultiBoxPrior",
    "multibox_target": "MultiBoxTarget",
    "multibox_detection": "MultiBoxDetection",
    "box_iou": "box_iou",
    "box_nms": "box_nms",
    "roi_align": "ROIAlign",
    "index_add": "index_add",
    "index_update": "_npx_index_update",
}

for _npx_name, _op_name in _NPX_OPS.items():
    if _op_name is not None and _op_name in _reg._REGISTRY:
        globals()[_npx_name] = _make(_op_name)


def reshape_like(a, b):
    return a.reshape(b.shape)


def waitall():
    from ..ndarray import waitall as _w
    _w()


def load(fname):
    from ..ndarray import load as _l
    return _l(fname)


def save(fname, data):
    from ..ndarray import save as _s
    return _s(fname, data)


def seed(seed_state):
    """Parity: npx.random seeding alias of mx.random.seed."""
    from ..ops.random import seed as _seed
    _seed(seed_state)


# control flow rides the contrib implementations (parity: npx.foreach/
# while_loop/cond over src/operator/control_flow.cc)
def foreach(body, data, init_states):
    from ..ndarray.contrib import foreach as _f
    return _f(body, data, init_states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    from ..ndarray.contrib import while_loop as _w
    return _w(cond, func, loop_vars, max_iterations=max_iterations)


def cond(pred, then_func, else_func):
    from ..ndarray.contrib import cond as _c
    return _c(pred, then_func, else_func)
