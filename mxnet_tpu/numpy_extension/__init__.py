"""mx.npx — numpy extensions (parity: python/mxnet/numpy_extension/ —
the `_npx_*` op namespace: nn ops with numpy arrays, sequence ops,
set_np/reset_np re-exports)."""
from __future__ import annotations

from ..util import (set_np, reset_np, is_np_array, is_np_shape,  # noqa: F401
                    use_np)
from ..context import (cpu, gpu, tpu, num_gpus, num_tpus,  # noqa: F401
                       current_context)
from ..ndarray.register import make_op_func as _make
from ..ops import registry as _reg

# nn/extension ops under their npx names (parity: _npx_* registrations)
_NPX_OPS = {
    "activation": "Activation",
    "batch_norm": "BatchNorm",
    "convolution": "Convolution",
    "deconvolution": "Deconvolution",
    "fully_connected": "FullyConnected",
    "pooling": "Pooling",
    "dropout": "Dropout",
    "embedding": "Embedding",
    "layer_norm": "LayerNorm",
    "group_norm": "GroupNorm",
    "instance_norm": "InstanceNorm",
    "leaky_relu": "LeakyReLU",
    "softmax": "softmax",
    "log_softmax": "log_softmax",
    "masked_softmax": "masked_softmax",
    "masked_log_softmax": "masked_log_softmax",
    "topk": "topk",
    "pick": "pick",
    "one_hot": "one_hot",
    "rnn": "RNN",
    "batch_dot": "batch_dot",
    "sequence_mask": "SequenceMask",
    "smooth_l1": "smooth_l1",
    "gamma": "gamma",
    "reshape_like": None,
    "broadcast_like": "broadcast_like",
    "arange_like": "arange_like",
    "shape_array": "shape_array",
    "gather_nd": "gather_nd",
    "scatter_nd": "scatter_nd",
    "slice": "slice",
    "slice_axis": "slice_axis",
    "slice_like": "slice_like",
    "ctc_loss": "CTCLoss",
    "sigmoid": "sigmoid",
    "relu": "relu",
}

for _npx_name, _op_name in _NPX_OPS.items():
    if _op_name is not None and _op_name in _reg._REGISTRY:
        globals()[_npx_name] = _make(_op_name)


def reshape_like(a, b):
    return a.reshape(b.shape)


def waitall():
    from ..ndarray import waitall as _w
    _w()


def load(fname):
    from ..ndarray import load as _l
    return _l(fname)


def save(fname, data):
    from ..ndarray import save as _s
    return _s(fname, data)
