"""Dependency-engine facade.

The reference schedules every op as an async closure with read/write
variable lists (``src/engine/threaded_engine.cc``).  On TPU, JAX's async
dispatch + XLA's dataflow ordering provide the same guarantees: ops issue
asynchronously, results are futures (``jax.Array``), and program order per
buffer is preserved by the runtime.  What survives here is the *API*:

- ``wait_all()``  — parity: ``Engine::WaitForAll`` / ``mx.nd.waitall()``
- ``wait_for_var(arr)`` — parity: ``WaitForVar`` (block on one array)
- ``set_bulk_size`` — kept as a no-op knob (XLA fusion replaces bulking)
- ``MXNET_ENGINE_TYPE=NaiveEngine`` — debug mode that synchronizes after
  every op so exceptions surface at the faulting call, mirroring the
  reference's naive engine (``src/engine/naive_engine.cc:51``).

Exception propagation parity (``threaded_engine.cc:422-434``): JAX raises
deferred errors at the first sync point; NaiveEngine mode makes that the
op call site itself.
"""
from __future__ import annotations

import jax

from .base import getenv

__all__ = ["naive_mode", "wait_all", "wait_for_var", "set_bulk_size", "bulk"]

_naive = (getenv("MXNET_ENGINE_TYPE", "") or "").lower() == "naiveengine"


def naive_mode() -> bool:
    """True when MXNET_ENGINE_TYPE=NaiveEngine (synchronous debug engine)."""
    return _naive


def set_naive_mode(flag: bool) -> None:
    global _naive
    _naive = bool(flag)


def wait_all() -> None:
    """Block until all outstanding device work is complete.

    Parity: Engine::WaitForAll (include/mxnet/engine.h) / mx.nd.waitall().
    """
    try:
        jax.effects_barrier()
    except Exception:
        pass
    for d in jax.live_arrays():
        try:
            d.block_until_ready()
        except Exception:
            # deferred errors belong to whoever reads the array; waitall in the
            # reference rethrows — match that.
            raise


def wait_for_var(value) -> None:
    """Block until one array's producing computation finished (WaitForVar)."""
    if hasattr(value, "wait_to_read"):
        value.wait_to_read()
    elif isinstance(value, jax.Array):
        value.block_until_ready()


_bulk_size = 0


def set_bulk_size(size: int) -> int:
    """Parity: mx.engine.set_bulk_size.  XLA fuses whole jitted steps, so
    bulking is a no-op; the knob is preserved for API compatibility."""
    global _bulk_size
    old, _bulk_size = _bulk_size, int(size)
    return old


class bulk:
    """``with mx.engine.bulk(n):`` context manager (no-op on TPU)."""

    def __init__(self, size: int):
        self._size = size

    def __enter__(self):
        self._old = set_bulk_size(self._size)
        return self

    def __exit__(self, *exc):
        set_bulk_size(self._old)
        return False
