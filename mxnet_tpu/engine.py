"""Dependency-engine facade.

The reference schedules every op as an async closure with read/write
variable lists (``src/engine/threaded_engine.cc``).  On TPU, JAX's async
dispatch + XLA's dataflow ordering provide the same guarantees: ops issue
asynchronously, results are futures (``jax.Array``), and program order per
buffer is preserved by the runtime.  What survives here is the *API*:

- ``wait_all()``  — parity: ``Engine::WaitForAll`` / ``mx.nd.waitall()``
- ``wait_for_var(arr)`` — parity: ``WaitForVar`` (block on one array)
- ``set_bulk_size`` — kept as a no-op knob (XLA fusion replaces bulking)
- ``MXNET_ENGINE_TYPE=NaiveEngine`` — debug mode that synchronizes after
  every op so exceptions surface at the faulting call, mirroring the
  reference's naive engine (``src/engine/naive_engine.cc:51``).

Exception propagation parity (``threaded_engine.cc:422-434``): JAX raises
deferred errors at the first sync point; NaiveEngine mode makes that the
op call site itself.
"""
from __future__ import annotations

import jax

from .base import getenv

__all__ = ["naive_mode", "wait_all", "wait_for_var", "set_bulk_size", "bulk"]

_naive = (getenv("MXNET_ENGINE_TYPE", "") or "").lower() == "naiveengine"


def naive_mode() -> bool:
    """True when MXNET_ENGINE_TYPE=NaiveEngine (synchronous debug engine)."""
    return _naive


def set_naive_mode(flag: bool) -> None:
    global _naive
    _naive = bool(flag)


def wait_all() -> None:
    """Block until all outstanding device work is complete.

    Parity: Engine::WaitForAll (include/mxnet/engine.h) / mx.nd.waitall().
    """
    try:
        jax.effects_barrier()
    except Exception:
        pass
    for d in jax.live_arrays():
        try:
            d.block_until_ready()
        except Exception:
            # deferred errors belong to whoever reads the array; waitall in the
            # reference rethrows — match that.
            raise


def wait_for_var(value) -> None:
    """Block until one array's producing computation finished (WaitForVar)."""
    if hasattr(value, "wait_to_read"):
        value.wait_to_read()
    elif isinstance(value, jax.Array):
        value.block_until_ready()


_bulk_size = 0


def set_bulk_size(size: int) -> int:
    """Parity: mx.engine.set_bulk_size.  XLA fuses whole jitted steps, so
    bulking is a no-op; the knob is preserved for API compatibility."""
    global _bulk_size
    old, _bulk_size = _bulk_size, int(size)
    return old


class bulk:
    """``with mx.engine.bulk(n):`` context manager (no-op on TPU)."""

    def __init__(self, size: int):
        self._size = size

    def __enter__(self):
        self._old = set_bulk_size(self._size)
        return self

    def __exit__(self, *exc):
        set_bulk_size(self._old)
        return False


# --------------------------------------------------------------------------
# NativeEngine: the C++ threaded dependency engine (src_native/engine.cc)
# scheduling HOST-side work — data-pipeline stages, custom-op callbacks,
# checkpoint IO — with the reference's read/write-variable ordering
# protocol (threaded_engine.h:71-215).  Device dataflow stays XLA's job.
# --------------------------------------------------------------------------

import ctypes as _ct
import threading as _threading

_CB_TYPE = _ct.CFUNCTYPE(None, _ct.c_void_p)


class NativeEngine:
    """Parity: Engine::Get() push/wait API over the native engine.

    >>> eng = NativeEngine(num_workers=4)
    >>> v = eng.new_var()
    >>> eng.push(lambda: work(), mutable_vars=[v])
    >>> eng.wait_for_var(v)
    """

    def __init__(self, num_workers: int = 0):
        from .io.native import get_lib
        self._lib = get_lib()
        self._lib.EngineCreate.restype = _ct.c_void_p
        self._lib.EngineNewVar.restype = _ct.c_int64
        self._lib.EnginePushAsync.restype = _ct.c_int
        self._lib.EngineWaitForVar.restype = _ct.c_int
        self._lib.EngineGetError.restype = _ct.c_int
        self._h = _ct.c_void_p(self._lib.EngineCreate(int(num_workers)))
        self._cbs = {}           # keep callbacks alive until they run
        self._cb_lock = _threading.Lock()
        self._cb_id = 0

    def new_var(self) -> int:
        """Parity: Engine::NewVariable."""
        return int(self._lib.EngineNewVar(self._h))

    def push(self, fn, const_vars=(), mutable_vars=()):
        """Parity: Engine::PushAsync — run ``fn()`` when all read deps
        (const_vars) and the exclusive write deps (mutable_vars) are
        available.  Exceptions surface at the next wait point."""
        with self._cb_lock:
            self._cb_id += 1
            cid = self._cb_id

        def trampoline(_arg, _fn=fn, _cid=cid):
            try:
                _fn()
            except BaseException as e:  # noqa: BLE001 — cross-ABI boundary
                self._lib.EngineSetError(
                    self._h, f"{type(e).__name__}: {e}".encode())
            finally:
                with self._cb_lock:
                    self._cbs.pop(_cid, None)

        cb = _CB_TYPE(trampoline)
        with self._cb_lock:
            self._cbs[cid] = cb
        n_use = len(const_vars)
        n_mut = len(mutable_vars)
        use = (_ct.c_int64 * max(n_use, 1))(*const_vars)
        mut = (_ct.c_int64 * max(n_mut, 1))(*mutable_vars)
        rc = self._lib.EnginePushAsync(self._h, cb, None, use, n_use,
                                       mut, n_mut)
        if rc != 0:
            from .base import MXNetError
            raise MXNetError("EnginePushAsync failed (unknown variable?)")

    def _check_error(self):
        buf = _ct.create_string_buffer(4096)
        n = self._lib.EngineGetError(self._h, buf, 4096)
        if n > 0:
            from .base import MXNetError
            raise MXNetError(
                f"engine op failed: {buf.value.decode(errors='replace')}")

    def wait_for_var(self, var: int):
        """Parity: Engine::WaitForVar + exception rethrow."""
        self._lib.EngineWaitForVar(self._h, _ct.c_int64(var))
        self._check_error()

    def wait_all(self):
        """Parity: Engine::WaitForAll + exception rethrow."""
        self._lib.EngineWaitForAll(self._h)
        self._check_error()

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.EngineDestroy(self._h)
                self._h = None
        except Exception:
            pass


_native_engine = None
_native_lock = _threading.Lock()


def native_engine() -> "NativeEngine":
    """The process-wide NativeEngine singleton (parity: Engine::Get();
    worker count from MXNET_CPU_WORKER_NTHREADS)."""
    global _native_engine
    with _native_lock:
        if _native_engine is None:
            from .base import getenv_int
            _native_engine = NativeEngine(
                getenv_int("MXNET_CPU_WORKER_NTHREADS", 0))
        return _native_engine
