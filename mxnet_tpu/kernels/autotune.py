"""Measured autotuner over a KernelSpec's config space.

TVM-style (PAPERS.md arxiv 1802.04799) but exhaustive rather than
model-guided: config spaces here are a handful of block-size/layout
candidates, so the tuner simply measures each through the
``benchmark/opperf.py`` timing harness (median-of-runs wall time,
device-synced per call) and commits the argmin.  Configs that fail to
build/lower for a shape are skipped, not fatal — a spec's default
config is always in the candidate set, so the winner is never slower
than the untuned default on the shapes measured.

Every measured run ticks ``kernel.tune_measurements`` and the total
wall time ticks ``kernel.tune_ms`` — the two signals ``kernel_smoke``
asserts are ZERO on a warm-cache relaunch.
"""
from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import registry as _kreg
from .registry import _C_TUNE_MS, _C_TUNE_RUNS

__all__ = ["candidates", "tune", "tune_registered"]


def _time_loop(fn, warmup: int, runs: int) -> float:
    """Median wall ms — the opperf harness's loop, imported so the
    tuner and the benchmark report measure identically (a local copy
    is kept only for contexts where ``benchmark`` isn't on the path)."""
    try:
        from benchmark.opperf import _time_loop as impl
        return impl(fn, warmup, runs)
    except ImportError:
        for _ in range(warmup):
            fn()
        samples = []
        for _ in range(runs):
            t0 = time.perf_counter()
            fn()
            samples.append(time.perf_counter() - t0)
        samples.sort()
        return samples[len(samples) // 2] * 1e3


def candidates(spec) -> List[Dict[str, Any]]:
    """The full cartesian product of the config space, default first
    (so ties resolve to the untuned behavior)."""
    keys = sorted(spec.config_space)
    out = [dict(spec.default_config)]
    for combo in itertools.product(*(spec.config_space[k] for k in keys)):
        cfg = dict(spec.default_config)
        cfg.update(zip(keys, combo))
        if cfg not in out:
            out.append(cfg)
    return out


def tune(spec, arrays: Sequence[Any], params: Optional[dict] = None,
         warmup: int = 1, runs: int = 3, verbose: bool = False
         ) -> Tuple[Dict[str, Any], float, List[dict]]:
    """Measure every candidate config on ``arrays``; returns
    ``(best_config, best_ms, rows)`` where rows carry the per-config
    table ``opperf --tune`` prints."""
    import jax

    params = params or {}
    t_start = time.perf_counter()
    rows: List[dict] = []
    best_cfg, best_ms = dict(spec.default_config), float("inf")
    for cfg in candidates(spec):

        def run_once(cfg=cfg):
            jax.block_until_ready(spec.run(cfg, *arrays, **params))
            _C_TUNE_RUNS.inc()

        try:
            run_once()                       # build/compile probe
            ms = _time_loop(run_once, warmup, runs)
        except Exception as e:               # config invalid for shape
            rows.append({"kernel": spec.name, "config": cfg, "ms": None,
                         "error": f"{type(e).__name__}"})
            if verbose:
                print(f"    {cfg}  FAILED ({type(e).__name__})")
            continue
        rows.append({"kernel": spec.name, "config": cfg,
                     "ms": round(ms, 4)})
        if verbose:
            print(f"    {cfg}  {ms:9.4f} ms")
        if ms < best_ms:
            best_cfg, best_ms = cfg, ms
    _C_TUNE_MS.inc((time.perf_counter() - t_start) * 1e3)
    if best_ms == float("inf"):              # nothing ran: keep default
        best_ms = 0.0
    return best_cfg, best_ms, rows


def tune_registered(names: Optional[Sequence[str]] = None,
                    warmup: int = 1, runs: int = 3,
                    verbose: bool = False) -> List[dict]:
    """Drive the tuner over each kernel's shape grid and commit the
    winners (memo + persistent cache).  The ``opperf --tune`` backend.

    Returns one row per (kernel, case, config) measurement, plus a
    ``winner`` row per case.

    Winner commits across the whole sweep batch into a single
    read-merge-replace cache write (``cache.batched_store``) instead of
    paying one lock+reread+rewrite per winner.
    """
    from . import cache as _cache
    with _cache.batched_store():
        return _tune_registered(names, warmup, runs, verbose)


def _tune_registered(names, warmup, runs, verbose) -> List[dict]:
    all_rows: List[dict] = []
    for name in (list(names) if names else _kreg.list_kernels()):
        spec = _kreg.get_kernel(name)
        if spec.make_args is None or not spec.tune_grid:
            if verbose:
                print(f"# {name}: no tune grid, skipped")
            continue
        for case in spec.tune_grid:
            arrays, params = spec.make_args(case)
            sig, dtype = spec.signature(*arrays, **params)
            if verbose:
                print(f"# tune {name} [{sig} {dtype}]")
            cfg, ms, rows = tune(spec, arrays, params=params,
                                 warmup=warmup, runs=runs, verbose=verbose)
            key = _kreg.commit(spec, sig, dtype, cfg, ms)
            for r in rows:
                r.update({"sig": sig, "dtype": dtype})
            all_rows.extend(rows)
            all_rows.append({"kernel": name, "sig": sig, "dtype": dtype,
                             "winner": cfg, "ms": round(ms, 4),
                             "key": key})
            if verbose:
                print(f"  -> winner {cfg}  {ms:.4f} ms  ({key})")
    return all_rows
