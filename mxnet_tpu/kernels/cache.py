"""Persistent kernel-autotune cache (the TVM-style pay-once store).

One versioned JSON document per fleet-shared directory
(``$MXNET_KERNEL_CACHE_DIR/kernel_cache.json``): measured winning
configs keyed by the full tuning key (op | kernel version | backend |
device count | dtype | shape signature — see ``registry.cache_key``).
A fresh process or a new serving replica looks a config up here instead
of re-measuring, so tuning cost is paid once per fleet, not once per
process (PAPERS.md TVM, arxiv 1802.04799).

Durability/corruption contract (shared with the checkpoint layer):

- writes go tmp → flush → fsync → ``os.replace`` → dir fsync
  (checkpoint.py's rename protocol), so a crashed tuner can never
  publish a torn file;
- loads treat ANY defect — missing file, bad JSON, wrong format tag,
  stale format version, non-dict entries — as an empty cache.  The
  failure mode is re-tuning, never crashing.

With ``MXNET_KERNEL_CACHE_DIR`` unset the cache is memory-only (the
in-process memo in ``registry`` still deduplicates within a process).
"""
from __future__ import annotations

import json
import os
import threading
from contextlib import contextmanager
from typing import Dict, Optional

FORMAT = "mxnet-tpu-kernel-cache"
VERSION = 1
FILENAME = "kernel_cache.json"

_LOCK = threading.Lock()

# batched-commit buffer: inside a batched_store() block, store() calls
# merge here instead of each paying a full lock+reread+rewrite cycle;
# one read-merge-replace write lands on block exit (the opperf --tune
# sweep commits N winners with ONE disk write)
_PENDING: Dict[str, dict] = {}
_BATCH_DEPTH = 0


def cache_dir() -> Optional[str]:
    """The fleet-shared cache directory, or None for memory-only."""
    return os.environ.get("MXNET_KERNEL_CACHE_DIR") or None


def cache_path() -> Optional[str]:
    d = cache_dir()
    return os.path.join(d, FILENAME) if d else None


def load() -> Dict[str, dict]:
    """Entries from disk: ``{key: {"config": {...}, "ms": float}}``.

    Empty dict on every defect (missing/corrupt/stale-version file) —
    the caller re-tunes instead of crashing, and the next ``store``
    overwrites the bad file.
    """
    path = cache_path()
    if path is None:
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("format") != FORMAT \
            or doc.get("version") != VERSION:
        return {}
    entries = doc.get("entries")
    if not isinstance(entries, dict):
        return {}
    return {k: v for k, v in entries.items()
            if isinstance(v, dict) and isinstance(v.get("config"), dict)}


def store(entries: Dict[str, dict]) -> bool:
    """Merge ``entries`` into the on-disk document atomically.

    Read-merge-replace under a process lock: concurrent tuners in one
    process can't drop each other's commits, and the rename keeps a
    reader (or a crash) from ever observing a torn file.  Returns False
    (memory-only) when no cache dir is configured.  Inside a
    :func:`batched_store` block the entries are buffered instead and
    land in one write when the block exits.
    """
    if cache_path() is None:
        return False
    with _LOCK:
        if _BATCH_DEPTH > 0:
            _PENDING.update(entries)
            return True
    return _write_merged(entries)


def _write_merged(entries: Dict[str, dict]) -> bool:
    path = cache_path()
    if path is None:
        return False
    from ..checkpoint import _fsync_dir
    with _LOCK:
        merged = load()
        merged.update(entries)
        doc = {"format": FORMAT, "version": VERSION, "entries": merged}
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(path))
    return True


@contextmanager
def batched_store():
    """Coalesce every :func:`store` call in the block into ONE
    read-merge-replace write on exit.  A tune sweep over many cases
    (``opperf --tune`` → ``autotune.tune_registered``) wraps itself in
    this so each winner costs a dict update, not a full
    lock+reread+rewrite of the cache file.  Re-entrant; the write
    happens when the outermost block exits (even on error — measured
    winners are never dropped)."""
    global _BATCH_DEPTH
    with _LOCK:
        _BATCH_DEPTH += 1
    try:
        yield
    finally:
        with _LOCK:
            _BATCH_DEPTH -= 1
            flush = dict(_PENDING) if (_BATCH_DEPTH == 0
                                       and _PENDING) else None
            if flush is not None:
                _PENDING.clear()
        if flush is not None:
            _write_merged(flush)
