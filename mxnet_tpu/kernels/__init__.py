"""Custom-kernel layer: registry + autotuner + persistent cache.

Hot ops (flash attention, fused LayerNorm+residual, the ZeRO
flatten/pad layout) register a :class:`KernelSpec` here — Pallas
implementation, tunable config space, XLA fallback/oracle — and
resolve their configs through :func:`resolve` instead of reading env
vars per call.  Tuned winners persist in a fleet-shared JSON cache
(``MXNET_KERNEL_CACHE_DIR``) written with the checkpoint layer's
atomic rename protocol; see docs/ARCHITECTURE.md "Custom kernels &
autotune cache".
"""
from . import cache  # noqa: F401
from .cache import cache_dir, cache_path  # noqa: F401
from .registry import (KernelSpec, register_kernel, get_kernel,  # noqa: F401
                       list_kernels, resolve, commit, invalidate,
                       warm_cache, cache_key, record_fallback, stats,
                       tune_enabled)
from .autotune import tune, tune_registered, candidates  # noqa: F401

__all__ = ["KernelSpec", "register_kernel", "get_kernel", "list_kernels",
           "resolve", "commit", "invalidate", "warm_cache", "cache_key",
           "record_fallback", "stats", "tune_enabled", "tune",
           "tune_registered", "candidates", "cache", "cache_dir",
           "cache_path"]
