"""Kernel registry: Pallas implementations + tunable configs + oracles.

Where ``ops/registry.py`` answers *which function* implements an op,
this registry answers *how that function's hand-written kernel should
be configured* on the current machine: each :class:`KernelSpec` names a
Pallas implementation, its tunable config space (block sizes,
pipelining depth, layout multiples), and an XLA fallback that doubles
as the numerics oracle parity tests pin the kernel against.

Config lookup order (see docs/ARCHITECTURE.md "Custom kernels"):

1. env override — handled at the call site (e.g. attention.py's
   ``MXNET_TPU_FLASH_BLOCK_Q/_K``), which must ``invalidate()`` the
   kernel when the override changes;
2. in-process memo — steady state, two dict lookups per call;
3. on-disk cache (``MXNET_KERNEL_CACHE_DIR``) — ticks
   ``kernel.cache_hits`` once per first-resolution;
4. the autotuner, when tuning is allowed (``MXNET_KERNEL_TUNE=1`` or an
   explicit ``--tune`` run) and measurement inputs are at hand;
5. the spec's default config — ticks ``kernel.cache_misses``.

Cache key anatomy::

    <op>|v<kernel version>|<backend>|ndev<N>|<dtype>|<shape signature>

The kernel version participates in the key, so bumping a spec's
``version`` after a kernel rewrite invalidates every stale entry by
construction — old entries simply stop matching.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import telemetry
from ..base import MXNetError

__all__ = ["KernelSpec", "register_kernel", "get_kernel", "list_kernels",
           "resolve", "commit", "invalidate", "warm_cache", "cache_key",
           "record_fallback", "stats", "tune_enabled"]

# kernel-layer health counters (created eagerly in telemetry.py so
# profiler.counters() and the step-record deltas always see the keys)
_C_HITS = telemetry.counter("kernel.cache_hits")
_C_MISSES = telemetry.counter("kernel.cache_misses")
_C_TUNE_MS = telemetry.counter("kernel.tune_ms")
_C_TUNE_RUNS = telemetry.counter("kernel.tune_measurements")
_C_FALLBACKS = telemetry.counter("kernel.fallbacks")
_C_WARM = telemetry.counter("kernel.warm_loaded")

_LOCK = threading.Lock()


class KernelSpec:
    """One registered kernel: Pallas path, config space, XLA oracle.

    ``run(config, *arrays, **params)``
        execute the Pallas implementation under ``config``.
    ``fallback(*arrays, **params)``
        the XLA lowering — the production fallback when the Pallas path
        can't run, and the numerics oracle parity tests compare against.
    ``signature(*arrays, **params) -> (sig, dtype)``
        bucketed shape signature + dtype string for the cache key.
    ``make_args(case) -> (arrays, params)``
        build concrete measurement inputs from one ``tune_grid`` case —
        the bridge to the ``benchmark/opperf.py`` tuning harness.
    ``version``
        bump after any kernel/layout rewrite; participates in the cache
        key, so stale tuned entries stop matching instead of lying.
    """

    __slots__ = ("name", "version", "run", "fallback", "config_space",
                 "default_config", "signature", "make_args", "tune_grid")

    def __init__(self, name: str, *, version: int,
                 run: Callable, fallback: Callable,
                 config_space: Dict[str, Sequence[Any]],
                 default_config: Dict[str, Any],
                 signature: Callable,
                 make_args: Optional[Callable] = None,
                 tune_grid: Sequence[dict] = ()):
        self.name = name
        self.version = int(version)
        self.run = run
        self.fallback = fallback
        self.config_space = {k: tuple(v) for k, v in config_space.items()}
        self.default_config = dict(default_config)
        self.signature = signature
        self.make_args = make_args
        self.tune_grid = tuple(tune_grid)

    def __repr__(self):
        return f"<KernelSpec {self.name} v{self.version}>"


_SPECS: Dict[str, KernelSpec] = {}

# key → (config, source) where source ∈ {"disk", "tuned", "default"}.
# The steady-state lookup is this dict — a "default" entry is upgraded
# in place if a later resolution is allowed to tune.
_MEMO: Dict[str, Tuple[Dict[str, Any], str]] = {}

# one parse of the on-disk JSON per process (re-read when the cache dir
# changes or after invalidate() — tests flip both)
_DISK: Dict[str, Any] = {"dir": False, "entries": None}

_TOPO: Optional[Tuple[str, int]] = None


def register_kernel(spec: KernelSpec) -> KernelSpec:
    if spec.name in _SPECS:
        raise MXNetError(f"kernel {spec.name!r} registered twice")
    _SPECS[spec.name] = spec
    return spec


def get_kernel(name: str) -> KernelSpec:
    try:
        return _SPECS[name]
    except KeyError:
        raise MXNetError(f"unknown kernel {name!r}") from None


def list_kernels() -> List[str]:
    return sorted(_SPECS)


def tune_enabled() -> bool:
    """The MXNET_KERNEL_TUNE switch: allow measuring on first encounter
    of an untuned key (stalls that step — telemetry records it)."""
    return os.environ.get("MXNET_KERNEL_TUNE", "0") == "1"


def _topology() -> Tuple[str, int]:
    global _TOPO
    if _TOPO is None:
        import jax
        _TOPO = (jax.default_backend(), jax.device_count())
    return _TOPO


def cache_key(spec: KernelSpec, sig: str, dtype: str) -> str:
    backend, ndev = _topology()
    return f"{spec.name}|v{spec.version}|{backend}|ndev{ndev}|{dtype}|{sig}"


def _disk_entries() -> Dict[str, dict]:
    from . import cache
    d = cache.cache_dir()
    if _DISK["entries"] is None or _DISK["dir"] != d:
        _DISK["dir"] = d
        _DISK["entries"] = cache.load()
    return _DISK["entries"]


def resolve(name: str, sig: str, dtype: str, *,
            tune_args: Optional[tuple] = None,
            allow_tune: Optional[bool] = None) -> Dict[str, Any]:
    """The config for one (kernel, shape-sig, dtype) on this topology.

    ``tune_args`` — optional ``(arrays, params)`` measurement inputs
    from the live call site; only consulted when tuning is allowed
    (``allow_tune``, defaulting to the MXNET_KERNEL_TUNE switch).
    Steady state is one memo lookup; the hit/miss counters tick only on
    the FIRST resolution of a key in this process.
    """
    spec = get_kernel(name)
    key = cache_key(spec, sig, dtype)
    can_tune = ((tune_enabled() if allow_tune is None else allow_tune)
                and tune_args is not None)
    with _LOCK:
        hit = _MEMO.get(key)
        if hit is not None and not (hit[1] == "default" and can_tune):
            return hit[0]
        entry = _disk_entries().get(key)
        if entry is not None:
            cfg = dict(entry["config"])
            _MEMO[key] = (cfg, "disk")
            _C_HITS.inc()
            return cfg
    if can_tune:
        from . import autotune
        arrays, params = tune_args
        cfg, ms, _rows = autotune.tune(spec, arrays, params=params)
        commit(spec, sig, dtype, cfg, ms)
        return cfg
    with _LOCK:
        if _MEMO.get(key) is None:
            _MEMO[key] = (dict(spec.default_config), "default")
            _C_MISSES.inc()
        return _MEMO[key][0]


def commit(spec: KernelSpec, sig: str, dtype: str,
           config: Dict[str, Any], ms: Optional[float] = None) -> str:
    """Record a tuned winner: in-process memo + the persistent cache
    (atomic merge-replace; memory-only when no cache dir is set)."""
    from . import cache
    key = cache_key(spec, sig, dtype)
    entry: Dict[str, Any] = {"config": dict(config),
                             "kernel_version": spec.version}
    if ms is not None:
        entry["ms"] = round(float(ms), 4)
    with _LOCK:
        _MEMO[key] = (dict(config), "tuned")
        entries = _disk_entries()
        entries[key] = entry
    cache.store({key: entry})
    return key


def invalidate(name: Optional[str] = None) -> None:
    """Drop in-process resolutions (all kernels, or one) and the cached
    disk snapshot.  Call sites use this when an env override changes;
    the on-disk file itself is never touched."""
    with _LOCK:
        if name is None:
            _MEMO.clear()
        else:
            for k in [k for k in _MEMO if k.split("|", 1)[0] == name]:
                del _MEMO[k]
        _DISK["entries"] = None


def warm_cache() -> int:
    """Prefetch every on-disk entry matching a registered kernel (at
    its current version) into the in-process memo — a serving replica's
    warmup calls this so its first request never waits on a cache-file
    parse, let alone a tune.  Returns the number of entries loaded and
    ticks ``kernel.warm_loaded`` by it, so warmup callers
    (serving.Engine.warmup, the decode engine) can log and assert the
    prefetch instead of firing it blind."""
    n = 0
    with _LOCK:
        for key, entry in _disk_entries().items():
            spec = _SPECS.get(key.split("|", 1)[0])
            if spec is None or f"|v{spec.version}|" not in key:
                continue
            if key not in _MEMO:
                _MEMO[key] = (dict(entry["config"]), "disk")
                _C_HITS.inc()
                n += 1
    if n:
        _C_WARM.inc(n)
    return n


def record_fallback(name: str) -> None:
    """Account one dispatch that took the XLA fallback instead of the
    registered Pallas path (build/lowering failure, unsupported case)."""
    _C_FALLBACKS.inc()
    telemetry.counter(f"kernel.{name}.fallbacks").inc()


def stats() -> Dict[str, float]:
    """Snapshot of the kernel-layer counters (see profiler.counters)."""
    return {"cache_hits": _C_HITS.value,
            "cache_misses": _C_MISSES.value,
            "tune_ms": _C_TUNE_MS.value,
            "tune_measurements": _C_TUNE_RUNS.value,
            "fallbacks": _C_FALLBACKS.value,
            "resolved": len(_MEMO)}
