"""Executable-artifact store: pay compile once per fleet, not per
process.

``store.py`` holds the content-addressed on-disk store of AOT-serialized
XLA executables (``MXNET_ARTIFACT_DIR``); every compiled-executable
cache in the stack — op funnel, cached whole-step, fused optimizer
step, serving buckets, decode executables, SPMD trainer steps —
consults it before compiling and commits into it after.  See
docs/ARCHITECTURE.md "Executable artifact store".
"""
from .store import (Artifact, FORMAT, VERSION, SUFFIX,  # noqa: F401
                    store_dir, enabled, max_bytes, env_fingerprint,
                    artifact_key, artifact_path, save, load, load_all,
                    stats)

__all__ = ["Artifact", "FORMAT", "VERSION", "SUFFIX", "store_dir",
           "enabled", "max_bytes", "env_fingerprint", "artifact_key",
           "artifact_path", "save", "load", "load_all", "stats"]
