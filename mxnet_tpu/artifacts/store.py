"""Content-addressed executable-artifact store (zero-compile cold start).

One on-disk store (``MXNET_ARTIFACT_DIR``) unifying the five
independently-grown compile caches behind a single pay-once protocol
(PAPERS.md TVM 1802.04799; the whole-program AOT argument of
1810.09868): op-funnel jit entries (``ops/registry._JitEntry``),
whole-step captures (``imperative/cached_step``), fused optimizer-step
families (``optimizer/fused_step``), serving buckets + decode
executables (``serving/``), and SPMD trainer steps
(``parallel/trainer``).  Values are REAL AOT-serialized executables
(``jax.experimental.serialize_executable``) — a warm process
deserializes and dispatches without ever invoking XLA.

Key anatomy — artifacts strand by construction, they are never
invalidated in place::

    sha256(FORMAT | VERSION | kind | signature
           | amp policy.cache_token() | jax/jaxlib versions
           | backend | device count)

``signature`` is the caller's content signature: the structure /
shape-dtype key the in-process cache already uses (a serving bucket
key, a cached-step structure key, a fused-step family+sig, an SPMD
step sig) — anything whose ``repr`` is stable across processes.  A jax
upgrade, an ``amp.init`` flip, a different backend, or a new device
count each mint different hashes, so stale executables simply stop
matching.

Durability (the kernels/cache.py protocol, generalized): commits go
tmp → flush → fsync → ``os.replace`` → dir fsync, so a crashed writer
never publishes a torn artifact.  Loads treat ANY defect — missing
file, bad pickle, header mismatch, ``deserialize_and_load`` raising on
version skew — as a miss (ticking ``artifact.deserialize_failures``
for real corruption/skew): the failure mode is recompiling, never
crashing.

Telemetry: ``artifact.{hits,misses,saves,bytes,load_ms,
deserialize_failures}`` (eager in telemetry.py; per-step deltas ride
the step record's ``artifact`` section).
"""
from __future__ import annotations

import hashlib
import os
import pickle
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from .. import telemetry

__all__ = ["Artifact", "FORMAT", "VERSION", "SUFFIX", "store_dir",
           "enabled", "max_bytes", "env_fingerprint", "artifact_key",
           "artifact_path", "save", "load", "load_all", "stats"]

FORMAT = "mxnet-tpu-artifact"
VERSION = 1
SUFFIX = ".mxart"

_LOCK = threading.Lock()

# store-health counters (created eagerly in telemetry.py so
# profiler.counters() and the step-record deltas always see the keys)
_C_HITS = telemetry.counter("artifact.hits")
_C_MISSES = telemetry.counter("artifact.misses")
_C_SAVES = telemetry.counter("artifact.saves")
_C_BYTES = telemetry.counter("artifact.bytes")
_C_LOAD_MS = telemetry.counter("artifact.load_ms")
_C_DESER_FAIL = telemetry.counter("artifact.deserialize_failures")


def store_dir() -> Optional[str]:
    """The artifact directory, or None when the store is off.  Read
    per call (like the kernel cache dir) so tests and long-lived
    processes can flip it live."""
    return os.environ.get("MXNET_ARTIFACT_DIR") or None


def enabled() -> bool:
    return store_dir() is not None


def max_bytes() -> Optional[int]:
    """MXNET_ARTIFACT_MAX_MB: total on-disk budget; oldest artifacts
    (by mtime) are evicted past it.  None/unparseable → unbounded."""
    raw = os.environ.get("MXNET_ARTIFACT_MAX_MB")
    if not raw:
        return None
    try:
        mb = float(raw)
    except ValueError:
        return None
    return int(mb * 1048576) if mb > 0 else None


def env_fingerprint() -> tuple:
    """The platform part of every key: an executable serialized under
    one jax/jaxlib/backend/device-count never loads under another."""
    import jax
    import jaxlib
    return (jax.__version__, jaxlib.__version__,
            jax.default_backend(), jax.device_count())


def _key_material(kind: str, signature: Any) -> str:
    from ..amp import policy as _amp_policy
    return repr((FORMAT, VERSION, str(kind), signature,
                 _amp_policy.cache_token(), env_fingerprint()))


def artifact_key(kind: str, signature: Any) -> str:
    """Content hash of (kind, signature, AMP token, platform)."""
    return hashlib.sha256(_key_material(kind, signature).encode()).hexdigest()


def artifact_path(kind: str, signature: Any) -> Optional[str]:
    d = store_dir()
    if d is None:
        return None
    return os.path.join(d, f"{kind}-{artifact_key(kind, signature)[:32]}"
                           f"{SUFFIX}")


class Artifact:
    """One loaded artifact: the ready-to-dispatch executable plus the
    side-channel metadata the save recorded (output treedefs, exec
    keys, owner fingerprints — whatever the caller needs to re-install
    the executable without re-tracing)."""

    __slots__ = ("compiled", "meta", "kind", "nbytes")

    def __init__(self, compiled, meta, kind, nbytes):
        self.compiled = compiled
        self.meta = meta
        self.kind = kind
        self.nbytes = nbytes


def save(kind: str, signature: Any, compiled, meta: Optional[dict] = None,
         ) -> bool:
    """Serialize ``compiled`` (a ``jax.stages.Compiled``) and commit it
    atomically under its content key.  Returns False — never raises —
    when the store is off or the executable declines serialization
    (some backends/executables can't round-trip; the in-process cache
    still has it, nothing is lost)."""
    d = store_dir()
    if d is None:
        return False
    try:
        from jax.experimental import serialize_executable as _se
        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps(
            {"format": FORMAT, "version": VERSION, "kind": str(kind),
             "key_material": _key_material(kind, signature),
             "signature": signature, "meta": dict(meta or {}),
             "payload": payload, "in_tree": in_tree, "out_tree": out_tree},
            protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        return False
    path = artifact_path(kind, signature)
    try:
        with _LOCK:
            os.makedirs(d, exist_ok=True)
            from ..checkpoint import _fsync_dir
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _fsync_dir(d)
            _evict_over_budget(d, keep=path)
    except OSError:
        return False
    _C_SAVES.inc()
    _C_BYTES.inc(len(blob))
    return True


def _evict_over_budget(d: str, keep: str) -> None:
    """Drop oldest artifacts (by mtime) until the directory fits
    MXNET_ARTIFACT_MAX_MB; the just-committed file is never evicted."""
    cap = max_bytes()
    if cap is None:
        return
    entries = []
    try:
        names = os.listdir(d)
    except OSError:
        return
    for name in names:
        if not name.endswith(SUFFIX):
            continue
        p = os.path.join(d, name)
        try:
            st = os.stat(p)
        except OSError:
            continue
        entries.append((st.st_mtime, st.st_size, p))
    total = sum(sz for _, sz, _ in entries)
    for _, sz, p in sorted(entries):
        if total <= cap:
            break
        if p == keep:
            continue
        try:
            os.remove(p)
            total -= sz
        except OSError:
            pass


def _read_doc(path: str) -> Optional[dict]:
    """Unpickle + header-check one artifact file; None on any defect
    (ticks ``artifact.deserialize_failures`` — a present-but-unusable
    file is corruption/skew, not a plain miss)."""
    try:
        with open(path, "rb") as f:
            doc = pickle.load(f)
    except Exception:
        _C_DESER_FAIL.inc()
        return None
    if not isinstance(doc, dict) or doc.get("format") != FORMAT \
            or doc.get("version") != VERSION:
        _C_DESER_FAIL.inc()
        return None
    return doc


def _deserialize(doc: dict):
    try:
        from jax.experimental import serialize_executable as _se
        return _se.deserialize_and_load(doc["payload"], doc["in_tree"],
                                        doc["out_tree"])
    except Exception:
        _C_DESER_FAIL.inc()
        return None


def load(kind: str, signature: Any) -> Optional[Artifact]:
    """The executable for (kind, signature) on this platform, or None
    (store off / miss / corrupt / version skew — callers recompile)."""
    path = artifact_path(kind, signature)
    if path is None:
        return None
    t0 = time.perf_counter()
    if not os.path.exists(path):
        _C_MISSES.inc()
        return None
    doc = _read_doc(path)
    if doc is None or doc.get("key_material") != _key_material(kind,
                                                               signature):
        _C_MISSES.inc()
        return None
    compiled = _deserialize(doc)
    if compiled is None:
        _C_MISSES.inc()
        return None
    _C_HITS.inc()
    _C_LOAD_MS.inc((time.perf_counter() - t0) * 1e3)
    try:
        nbytes = os.path.getsize(path)
    except OSError:
        nbytes = 0
    return Artifact(compiled, doc.get("meta") or {}, kind, nbytes)


def load_all(kind: str) -> Iterator[Artifact]:
    """Every loadable artifact of ``kind`` valid on this platform —
    the one-call warmup drain (``SPMDTrainer.warm_start``,
    ``DecodeEngine.warmup``).  Stale entries (other amp token / jax
    version / backend) are silently skipped; corrupt ones tick
    ``artifact.deserialize_failures``.  Hit/load_ms accounting matches
    :func:`load`."""
    d = store_dir()
    if d is None:
        return
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return
    prefix = f"{kind}-"
    for name in names:
        if not name.startswith(prefix) or not name.endswith(SUFFIX):
            continue
        t0 = time.perf_counter()
        doc = _read_doc(os.path.join(d, name))
        if doc is None or doc.get("kind") != kind:
            continue
        # validity: re-deriving the key material from the stored
        # signature must reproduce what the writer recorded — a
        # mismatch means the artifact was minted under a different
        # amp token / jax version / topology and is stranded
        if doc.get("key_material") != _key_material(kind,
                                                    doc.get("signature")):
            continue
        compiled = _deserialize(doc)
        if compiled is None:
            continue
        _C_HITS.inc()
        _C_LOAD_MS.inc((time.perf_counter() - t0) * 1e3)
        try:
            nbytes = os.path.getsize(os.path.join(d, name))
        except OSError:
            nbytes = 0
        yield Artifact(compiled, doc.get("meta") or {}, kind, nbytes)


def stats() -> Dict[str, Any]:
    """Snapshot of the store counters plus the on-disk census."""
    out = {"hits": _C_HITS.value, "misses": _C_MISSES.value,
           "saves": _C_SAVES.value, "bytes": _C_BYTES.value,
           "load_ms": round(_C_LOAD_MS.value, 3),
           "deserialize_failures": _C_DESER_FAIL.value,
           "dir": store_dir(), "files": 0, "disk_bytes": 0}
    d = store_dir()
    if d is not None and os.path.isdir(d):
        for name in os.listdir(d):
            if name.endswith(SUFFIX):
                out["files"] += 1
                try:
                    out["disk_bytes"] += os.path.getsize(
                        os.path.join(d, name))
                except OSError:
                    pass
    return out
