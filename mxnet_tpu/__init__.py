"""mxnet_tpu — a TPU-native deep-learning framework with the capability
surface of Apache MXNet (see SURVEY.md at the repo root).

Import as ``import mxnet_tpu as mx``; the namespaces mirror the
reference: ``mx.nd``, ``mx.np``, ``mx.autograd``, ``mx.gluon``,
``mx.optimizer``, ``mx.kv``, ``mx.context``.
"""
__version__ = "0.1.0"

import os as _os

# Some environments (e.g. a sitecustomize that force-registers an
# accelerator backend) override the user's JAX_PLATFORMS at interpreter
# start — both the jax config AND the env var itself (it exports its
# own platform name).  Re-assert the user's explicit choice so
# ``JAX_PLATFORMS=cpu python script.py`` means what it says, but leave
# the injector's own value alone (re-asserting it would also clobber
# later programmatic jax.config.update("jax_platforms", ...) calls).
_want_platform = _os.environ.get("JAX_PLATFORMS")
if _want_platform and "axon" not in _want_platform:
    import jax as _jax
    if (_jax.config.jax_platforms or "") != _want_platform:
        _jax.config.update("jax_platforms", _want_platform)
        from jax._src import xla_bridge as _xb
        if _xb.backends_are_initialized():
            from jax.extend.backend import clear_backends as _cb
            _cb()

from .base import MXNetError
from .context import (Context, cpu, tpu, gpu, cpu_pinned, current_context,
                      num_gpus, num_tpus)
from . import base
from . import context
from . import engine
from . import autograd
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import random  # noqa: E402
from . import initializer  # noqa: E402
from . import optimizer  # noqa: E402
from . import lr_scheduler  # noqa: E402
from . import gluon  # noqa: E402
from . import kvstore  # noqa: E402
from . import kvstore as kv  # noqa: E402
from . import symbol  # noqa: E402
from . import symbol as sym  # noqa: E402
from . import numpy  # noqa: E402
from . import numpy as np  # noqa: E402
from . import numpy_extension as npx  # noqa: E402
from . import parallel  # noqa: E402
from . import profiler  # noqa: E402
from . import telemetry  # noqa: E402
from . import tracing  # noqa: E402
from . import serving  # noqa: E402
from . import embedding  # noqa: E402
from . import checkpoint  # noqa: E402
from . import data  # noqa: E402
from . import monitor  # noqa: E402
from . import amp  # noqa: E402
from . import test_utils  # noqa: E402
from . import util  # noqa: E402
from .util import is_np_array, set_np, reset_np  # noqa: E402
from . import runtime  # noqa: E402
from . import operator  # noqa: E402
from . import contrib  # noqa: E402
from . import callback  # noqa: E402
from . import visualization  # noqa: E402
from . import library  # noqa: E402
from . import rtc  # noqa: E402
from . import subgraph  # noqa: E402
from .visualization import print_summary, plot_network  # noqa: E402
from . import io  # noqa: E402
from . import image  # noqa: E402
from . import attribute  # noqa: E402
from .attribute import AttrScope  # noqa: E402
from . import name  # noqa: E402
from . import model  # noqa: E402
from . import error  # noqa: E402
from . import registry  # noqa: E402
from . import log  # noqa: E402
from . import executor  # noqa: E402

# large-tensor (int64) switch at import (parity: the reference's
# MXNET_USE_INT64_TENSOR_SIZE build flag; here a runtime env toggle)
if base.getenv_bool("MXNET_INT64_TENSOR_SIZE"):
    util.set_large_tensor(True)

# snapshot the built-in op set (ops registered by the package itself);
# later user/test/extension registrations are intentionally excluded
# from library-completeness contracts
ops.registry.freeze_builtin_snapshot()
