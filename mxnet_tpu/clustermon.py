"""Cluster-scope observability: rank-aware aggregation + Prometheus.

Telemetry (telemetry.py) and the flight recorder (tracing.py) are
strictly single-process; once the commit barrier (checkpoint.py) makes
multi-host the default failure domain, the first-order question stops
being "is this step slow" and becomes "WHICH rank made it slow, and
why".  This module is that layer, built on the same file-based rank
coordination the checkpoint barrier already proved out:

- **Spools**: with ``MXNET_CLUSTER_DIR`` set, every rank appends its
  per-step telemetry record (stamped ``rank``/``world`` — resolved
  through the checkpoint ``set_rank`` precedence chain, plus a
  thread-local override for threads-as-ranks harnesses) to
  ``<dir>/rank-<r>.jsonl``.  One JSON object per line, flushed per
  record, so a live cluster can be tailed from any host that mounts
  the shared directory.
- **Aggregator** (rank 0 only): a daemon thread tails all spools,
  joins records by per-rank step ordinal, and produces a cluster view:
  per-rank step-time skew, barrier-wait asymmetry, and a per-step
  critical-path decomposition (input wait / H2D / compile / collective
  / optimizer update / checkpoint) derived from tracing-span bucket
  deltas where tracing is live and record fields where it is not.
- **Straggler detector**: over a sliding window
  (``MXNET_CLUSTER_WINDOW`` joined steps) the slowest rank is named
  when its mean step time exceeds ``MXNET_STRAGGLER_FACTOR`` × the
  median of its peers, and its dominant cause is classified
  (``input_bound`` / ``compile_stall`` / ``ckpt_interference`` /
  ``comm_skew``) from the per-signal excess over the peer median.
  Results land in the ``cluster.straggler_rank`` /
  ``cluster.straggler_cause`` gauges with ONE log line per incident
  (re-logged only when the rank or cause changes).
- **Prometheus**: :func:`prometheus_text` renders the whole telemetry
  registry in text exposition format (``# TYPE`` lines, ``rank=""``
  label on every sample, histograms as summaries with reservoir
  quantiles).  ``GET /metrics`` on the serving server and a standalone
  ``MXNET_METRICS_PORT`` exporter for training runs serve it.

Disabled contract: with ``MXNET_CLUSTER_DIR`` and
``MXNET_METRICS_PORT`` unset nothing here runs — no spool files, no
aggregator or exporter thread, and the step path is bitwise identical
to the pre-clustermon build (telemetry's ``begin_step`` fast path is
untouched).  ``tools/cluster_report.py`` replays the same join +
detection over spools offline for post-mortems.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry

__all__ = ["rank_world", "set_thread_rank", "note_rank", "SpoolSink",
           "ClusterAggregator", "aggregator", "cluster_view",
           "join_by_step", "window_stats", "detect_straggler",
           "record_signals", "CAUSES",
           "prometheus_text", "parse_prometheus_text",
           "start_metrics_server", "stop_metrics_server",
           "metrics_server_address"]

_LOCK = threading.Lock()

_SPOOL_RE = re.compile(r"rank-(\d+)\.jsonl$")

# cluster-health metrics (created eagerly so profiler.counters() and a
# /metrics scrape always see the keys, zeros/none before the first
# aggregator pass)
_G_RANKS = telemetry.gauge("cluster.ranks")
_G_SKEW = telemetry.gauge("cluster.step_ms_skew")
_G_BARRIER_SKEW = telemetry.gauge("cluster.barrier_wait_skew_ms")
_G_STRAGGLER = telemetry.gauge("cluster.straggler_rank")
_G_CAUSE = telemetry.gauge("cluster.straggler_cause")
_C_INCIDENTS = telemetry.counter("cluster.straggler_incidents")
_C_JOINED = telemetry.counter("cluster.joined_steps")


def _logger():
    from .log import get_logger
    return get_logger("mxnet_tpu.clustermon")


# -- rank/world resolution ---------------------------------------------------
# Precedence: per-thread override (threads-as-ranks harnesses) > the
# checkpoint chain (explicit env > DistKVStore's set_rank plumbing >
# jax.process_index()).  The checkpoint resolution is cached keyed on
# the inputs it depends on, so per-span stamping never pays a backend
# call.

_tls = threading.local()
_rank_cache: Dict[str, Any] = {"key": None, "rw": (0, 1)}


def set_thread_rank(rank: Optional[int], world: int = 1) -> None:
    """Pin (rank, world) for the CALLING thread only — how a
    threads-as-ranks harness gives each worker thread its own spool.
    ``None`` clears the override."""
    if rank is None:
        _tls.rw = None
    else:
        _tls.rw = (int(rank), max(1, int(world)))


def note_rank(rank: int, world: int) -> None:
    """Invalidate the cached process-level resolution (called by the
    dist kvstore right after ``checkpoint.set_rank`` so the next record
    picks the plumbed identity up immediately)."""
    with _LOCK:
        _rank_cache["key"] = None


def rank_world() -> Tuple[int, int]:
    """(rank, world) for stamping records and spans."""
    rw = getattr(_tls, "rw", None)
    if rw is not None:
        return rw
    return _process_rank_world()


def _process_rank_world() -> Tuple[int, int]:
    """The checkpoint-chain resolution only (no thread-local override)
    — what decides which PROCESS hosts the aggregator."""
    from . import checkpoint
    key = (os.environ.get("MXNET_CKPT_RANK"),
           os.environ.get("MXNET_CKPT_WORLD"),
           checkpoint._rank_override)
    with _LOCK:
        if key == _rank_cache["key"]:
            return _rank_cache["rw"]
    try:
        rw = checkpoint.rank_world()
    except Exception:
        rw = (0, 1)     # invalid env raises at save() where it matters
    with _LOCK:
        _rank_cache["key"] = key
        _rank_cache["rw"] = rw
    return rw


# -- per-rank spools ---------------------------------------------------------

class SpoolSink:
    """Telemetry sink appending each step record to the emitting rank's
    spool (``<dir>/rank-<r>.jsonl``).  A ``rank_step`` ordinal (this
    rank's Nth record) is stamped so the aggregator can join steps
    across ranks even when the process-global ``step`` counter
    interleaves (threads-as-ranks)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._files: Dict[int, Any] = {}
        self._counts: Dict[int, int] = {}
        self._lock = threading.Lock()

    def emit(self, record: dict) -> None:
        r = int(record.get("rank", 0))
        with self._lock:
            n = self._counts.get(r, 0) + 1
            self._counts[r] = n
            f = self._files.get(r)
            if f is None:
                path = os.path.join(self.directory, f"rank-{r}.jsonl")
                f = self._files[r] = open(path, "a", buffering=1)
        f.write(json.dumps(dict(record, rank_step=n)) + "\n")

    def close(self) -> None:
        with self._lock:
            for f in self._files.values():
                try:
                    f.close()
                except Exception:
                    pass
            self._files.clear()


# -- record signal extraction ------------------------------------------------

# straggler cause classes, in the order the ARCHITECTURE decision-rule
# table documents them
CAUSES = ("input_bound", "compile_stall", "ckpt_interference",
          "comm_skew")

_SIG_OF_CAUSE = {"input_bound": "input", "compile_stall": "compile",
                 "ckpt_interference": "checkpoint", "comm_skew": "comm"}
_CAUSE_OF_SIG = {v: k for k, v in _SIG_OF_CAUSE.items()}


def record_signals(rec: dict) -> Dict[str, float]:
    """Per-record attribution signals (ms) for the straggler
    classifier.  Span-bucket deltas (``critical_path``, present when
    tracing is live) and record fields measure overlapping intervals —
    ``max`` of the two is taken per signal rather than their sum so a
    traced run never double-counts."""
    cp = rec.get("critical_path") or {}
    ck = rec.get("checkpoint") or {}
    return {
        "input": max(float(rec.get("input_wait_ms") or 0.0),
                     float(cp.get("input_wait") or 0.0)),
        "compile": max(float(rec.get("compile_ms") or 0.0),
                       float(cp.get("compile") or 0.0)),
        "checkpoint": max(float(ck.get("barrier_wait_ms") or 0.0),
                          float(cp.get("checkpoint") or 0.0)),
        "comm": float(cp.get("collective") or 0.0),
    }


def join_by_step(by_rank: Dict[int, List[dict]]) -> Dict[int, Dict[int,
                                                                   dict]]:
    """Join records across ranks: {step: {rank: record}}.  The join key
    is the per-rank ``rank_step`` ordinal the spool sink stamps (the
    i-th record a rank emitted IS its i-th step), falling back to
    position for spools that predate the field."""
    joined: Dict[int, Dict[int, dict]] = {}
    for r, recs in by_rank.items():
        for i, rec in enumerate(recs):
            step = int(rec.get("rank_step", i + 1))
            joined.setdefault(step, {})[r] = rec
    return joined


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _median(vals: List[float]) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def window_stats(by_rank: Dict[int, List[dict]],
                 window: int) -> Dict[int, dict]:
    """Per-rank aggregates over the trailing ``window`` JOINED steps
    (only steps every rank has reported — a rank that is behind must
    not look fast because its slow steps haven't landed yet)."""
    joined = join_by_step(by_rank)
    ranks = sorted(by_rank)
    complete = sorted(s for s, per in joined.items()
                      if all(r in per for r in ranks))
    tail = complete[-window:] if window else complete
    stats: Dict[int, dict] = {}
    for r in ranks:
        recs = [joined[s][r] for s in tail]
        host = [float(x.get("host_ms") or 0.0) for x in recs]
        sigs = [record_signals(x) for x in recs]
        cps = [x.get("critical_path") or {} for x in recs]
        stats[r] = {
            "steps": len(recs),
            "host_ms_mean": _mean(host),
            "host_ms_max": max(host, default=0.0),
            "signals": {k: _mean([s[k] for s in sigs])
                        for k in ("input", "compile", "checkpoint",
                                  "comm")},
            "critical_path": {
                k: _mean([float(c.get(k) or 0.0) for c in cps])
                for k in ("input_wait", "h2d", "compile", "collective",
                          "optimizer", "checkpoint", "compute")},
            "barrier_wait_ms_mean": _mean(
                [float((x.get("checkpoint") or {})
                       .get("barrier_wait_ms") or 0.0) for x in recs]),
        }
    return stats


def detect_straggler(stats: Dict[int, dict],
                     factor: float) -> Optional[dict]:
    """Name the slowest rank in a window and classify its dominant
    cause.  Decision rule (docs/ARCHITECTURE.md "Cluster
    observability"): the slowest rank is a straggler when its mean
    step time exceeds ``factor`` × the median of the OTHER ranks';
    its cause is the signal with the largest excess over the peer
    median, or ``unknown`` when no signal explains ≥10% of the step
    -time excess (unattributed compute — a thermally-throttled chip
    looks like this)."""
    live = {r: s for r, s in stats.items() if s["steps"]}
    if len(live) < 2:
        return None
    slowest = max(live, key=lambda r: live[r]["host_ms_mean"])
    peers = [live[r]["host_ms_mean"] for r in live if r != slowest]
    med = _median(peers)
    mean = live[slowest]["host_ms_mean"]
    if med <= 0.0 or mean <= factor * med:
        return None
    excess = {
        sig: live[slowest]["signals"][sig]
        - _median([live[r]["signals"][sig] for r in live if r != slowest])
        for sig in ("input", "compile", "checkpoint", "comm")}
    total_excess = mean - med
    top = max(excess, key=lambda k: excess[k])
    if excess[top] <= 0.0 or excess[top] < 0.1 * total_excess:
        cause = "unknown"
    else:
        cause = _CAUSE_OF_SIG[top]
    return {"rank": slowest, "cause": cause,
            "ratio": mean / med, "step_ms": mean, "peer_ms": med,
            "excess_ms": {_CAUSE_OF_SIG[k]: round(v, 3)
                          for k, v in excess.items()}}


# -- the rank-0 aggregator ---------------------------------------------------

def _straggler_factor() -> float:
    v = os.environ.get("MXNET_STRAGGLER_FACTOR")
    try:
        return max(1.0, float(v)) if v else 1.5
    except ValueError:
        return 1.5


def _cluster_window() -> int:
    v = os.environ.get("MXNET_CLUSTER_WINDOW")
    try:
        return max(1, int(v)) if v else 20
    except ValueError:
        return 20


class ClusterAggregator:
    """Tails every ``rank-*.jsonl`` spool in ``directory``, joins
    records by step, and maintains the cluster view + gauges.  Owns an
    optional daemon thread (:meth:`start`); :meth:`poll` runs one pass
    synchronously so tests and the report tool stay deterministic."""

    def __init__(self, directory: str, window: Optional[int] = None,
                 factor: Optional[float] = None, poll_s: float = 0.5,
                 keep: int = 512):
        self.directory = directory
        self.window = window if window is not None else _cluster_window()
        self.factor = factor if factor is not None else _straggler_factor()
        self.poll_s = max(0.05, float(poll_s))
        self.keep = max(self.window * 4, keep)
        self._tails: Dict[str, Tuple[int, bytes]] = {}
        self._by_rank: Dict[int, List[dict]] = {}
        self._view: dict = {"ranks": {}, "straggler": None, "skew": None,
                            "window": self.window, "joined_steps": 0}
        self._joined_seen = 0
        self._incident: Optional[Tuple[int, str]] = None
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # -- spool tailing -------------------------------------------------------

    def _read_new(self) -> bool:
        """Drain complete new lines from every spool; True when any
        record arrived.  Offsets are byte-exact and a partial trailing
        line (a rank mid-write) is buffered until its newline lands."""
        grew = False
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            return False
        for name in names:
            m = _SPOOL_RE.match(name)
            if not m:
                continue
            rank = int(m.group(1))
            path = os.path.join(self.directory, name)
            off, buf = self._tails.get(path, (0, b""))
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    data = f.read()
            except OSError:
                continue
            if not data:
                continue
            off += len(data)
            buf += data
            *lines, buf = buf.split(b"\n")
            self._tails[path] = (off, buf)
            recs = self._by_rank.setdefault(rank, [])
            for ln in lines:
                if not ln.strip():
                    continue
                try:
                    recs.append(json.loads(ln))
                    grew = True
                except ValueError:
                    continue            # torn write; skip the line
            if len(recs) > self.keep:
                del recs[:len(recs) - self.keep]
        return grew

    # -- view / gauges -------------------------------------------------------

    def poll(self) -> dict:
        """One synchronous pass: tail spools, recompute the view,
        refresh gauges, log new incidents.  Returns the view."""
        with self._lock:
            grew = self._read_new()
            if grew or not self._view["ranks"]:
                self._recompute()
            return dict(self._view)

    def _recompute(self) -> None:
        stats = window_stats(self._by_rank, self.window)
        straggler = detect_straggler(stats, self.factor)
        means = [s["host_ms_mean"] for s in stats.values() if s["steps"]]
        barrier = [s["barrier_wait_ms_mean"] for s in stats.values()
                   if s["steps"]]
        joined = join_by_step(self._by_rank)
        ranks = sorted(self._by_rank)
        complete = sum(1 for per in joined.values()
                       if all(r in per for r in ranks))
        skew = None
        if len(means) >= 2:
            skew = {"step_ms": max(means) - min(means),
                    "step_ratio": max(means) / min(means)
                    if min(means) > 0 else None,
                    "barrier_wait_ms": max(barrier) - min(barrier)}
        self._view = {"ranks": stats, "straggler": straggler,
                      "skew": skew, "window": self.window,
                      "joined_steps": complete}
        # gauges: the scrapeable face of the view
        _G_RANKS.set(len(ranks))
        new_joined = complete - self._joined_seen
        if new_joined > 0:
            _C_JOINED.inc(new_joined)
            self._joined_seen = complete
        if skew:
            _G_SKEW.set(round(skew["step_ms"], 3))
            _G_BARRIER_SKEW.set(round(skew["barrier_wait_ms"], 3))
        if straggler is None:
            _G_STRAGGLER.set(-1)
            _G_CAUSE.set("none")
            self._incident = None
            return
        _G_STRAGGLER.set(int(straggler["rank"]))
        _G_CAUSE.set(straggler["cause"])
        incident = (int(straggler["rank"]), straggler["cause"])
        if incident != self._incident:    # once per incident
            self._incident = incident
            _C_INCIDENTS.inc()
            _logger().warning(
                "cluster straggler: rank %d is %.2fx the peer median "
                "(%.2f ms vs %.2f ms over the last %d joined steps); "
                "dominant cause: %s (excess ms %s)",
                straggler["rank"], straggler["ratio"],
                straggler["step_ms"], straggler["peer_ms"],
                self.window, straggler["cause"], straggler["excess_ms"])

    def view(self) -> dict:
        with self._lock:
            return dict(self._view)

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="mxnet-clustermon",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            try:
                self.poll()
            except Exception:
                _logger().exception("cluster aggregator poll failed")

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self._thread = None


_aggregator: Optional[ClusterAggregator] = None


def aggregator() -> Optional[ClusterAggregator]:
    """The live aggregator (rank 0 with MXNET_CLUSTER_DIR set), else
    None."""
    return _aggregator


def cluster_view() -> Optional[dict]:
    """The aggregator's current cluster view (None when not running)."""
    agg = _aggregator
    return agg.view() if agg is not None else None


def _on_cluster_dir(directory: Optional[str]) -> None:
    """telemetry's env-refresh hook: start/stop the aggregator as
    ``MXNET_CLUSTER_DIR`` appears/changes/vanishes.  Only the rank-0
    PROCESS runs one (the thread-local rank override is deliberately
    ignored: under threads-as-ranks any worker thread may trigger the
    env refresh, and the process as a whole is rank 0)."""
    global _aggregator
    if _aggregator is not None and \
            (directory is None or _aggregator.directory != directory):
        _aggregator.stop()
        _aggregator = None
    if directory and _aggregator is None and \
            _process_rank_world()[0] == 0:
        _aggregator = ClusterAggregator(directory)
        _aggregator.start()


# -- Prometheus text exposition ----------------------------------------------

_NAME_SANE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    return "mxnet_" + _NAME_SANE.sub("_", name)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels(d: Dict[str, Any]) -> str:
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(d.items()))
    return "{" + inner + "}"


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return repr(f) if f == f else "NaN"


def prometheus_text(extra_labels: Optional[Dict[str, str]] = None) -> str:
    """The whole telemetry registry in Prometheus text exposition
    format (v0.0.4).  Every sample carries a ``rank`` label (the
    MegaScale-style per-rank metrics plane: one scrape config, rank as
    the aggregation dimension); counters render as ``counter``, gauges
    as ``gauge`` (string-valued gauges like ``cluster.straggler_cause``
    become a ``1``-valued sample with the string in a label), and
    histograms as ``summary`` — reservoir p50/p95 quantiles plus exact
    ``_sum``/``_count``."""
    r, _w = rank_world()
    base = dict(extra_labels or {})
    base["rank"] = str(r)
    out: List[str] = []
    for name, m in telemetry.metrics().items():
        pname = _metric_name(name)
        if isinstance(m, telemetry.Counter):
            out.append(f"# TYPE {pname} counter")
            out.append(f"{pname}{_labels(base)} {_fmt(m.value)}")
        elif isinstance(m, telemetry.Gauge):
            v = m.value
            if v is None:
                continue
            out.append(f"# TYPE {pname} gauge")
            if isinstance(v, str):
                key = "cause" if name.endswith("cause") else "value"
                out.append(f"{pname}{_labels(dict(base, **{key: v}))} 1")
            else:
                out.append(f"{pname}{_labels(base)} {_fmt(v)}")
        elif isinstance(m, telemetry.Histogram):
            out.append(f"# TYPE {pname} summary")
            samples = sorted(m.samples())
            for q, qs in ((50, "0.5"), (95, "0.95")):
                if samples:
                    k = max(0, min(len(samples) - 1,
                                   round(q / 100 * (len(samples) - 1))))
                    out.append(f"{pname}{_labels(dict(base, quantile=qs))}"
                               f" {_fmt(samples[k])}")
            out.append(f"{pname}_sum{_labels(base)} {_fmt(m.total)}")
            out.append(f"{pname}_count{_labels(base)} {_fmt(m.count)}")
    return "\n".join(out) + "\n"


_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|NaN|[+-]Inf)"
    r"(?: -?[0-9]+)?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return (v.replace("\\\\", "\x00").replace("\\n", "\n")
            .replace('\\"', '"').replace("\x00", "\\"))


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[Dict[str,
                                                                  str],
                                                             float]]]:
    """Strict-ish exposition parser used by the tests and the CI
    scrape check: validates ``# TYPE`` lines and sample syntax, resolves
    label escapes, and requires every sample's base metric (modulo
    ``_sum``/``_count``/``_bucket`` suffixes) to have a preceding TYPE
    line.  Raises ``ValueError`` on any malformed line.  Returns
    {metric name: [(labels, value)]}."""
    types: Dict[str, str] = {}
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# HELP "):
                continue
            m = _TYPE_RE.match(line)
            if m is None:
                raise ValueError(f"line {i}: bad comment/TYPE line "
                                 f"{line!r}")
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: bad sample line {line!r}")
        name, rawlabels, val = m.group(1), m.group(2), m.group(3)
        base = re.sub(r"_(sum|count|bucket)$", "", name)
        if name not in types and base not in types:
            raise ValueError(f"line {i}: sample {name!r} has no "
                             f"preceding # TYPE line")
        labels = {}
        if rawlabels:
            consumed = 0
            for lm in _LABEL_RE.finditer(rawlabels):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
                consumed = lm.end()
            if rawlabels[consumed:].strip(", "):
                raise ValueError(f"line {i}: bad label syntax "
                                 f"{rawlabels!r}")
        out.setdefault(name, []).append((labels, float(val)))
    return out


# -- standalone /metrics exporter (training processes) -----------------------

_metrics_httpd = None
_metrics_thread = None
_metrics_addr: Optional[Tuple[str, int]] = None


def start_metrics_server(port: int = 0,
                         host: str = "0.0.0.0") -> Tuple[str, int]:
    """Serve ``GET /metrics`` (text exposition) + ``GET /healthz`` on a
    daemon thread — the scrape surface for training processes, which
    have no serving server.  Returns the bound ``(host, port)``
    (OS-assigned when ``port=0``).  Idempotent: an exporter already
    running keeps its socket."""
    global _metrics_httpd, _metrics_thread, _metrics_addr
    with _LOCK:
        if _metrics_httpd is not None:
            return _metrics_addr
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                if self.path.split("?", 1)[0] == "/metrics":
                    body = prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/healthz":
                    view = cluster_view()
                    body = json.dumps(
                        {"status": "ok", "rank": rank_world()[0],
                         "world": rank_world()[1],
                         "cluster": view}).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        _metrics_httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        _metrics_httpd.daemon_threads = True
        _metrics_thread = threading.Thread(
            target=_metrics_httpd.serve_forever,
            name="mxnet-metrics-exporter", daemon=True)
        _metrics_thread.start()
        _metrics_addr = _metrics_httpd.server_address[:2]
        return _metrics_addr


def stop_metrics_server() -> None:
    global _metrics_httpd, _metrics_thread, _metrics_addr
    with _LOCK:
        httpd, thread = _metrics_httpd, _metrics_thread
        _metrics_httpd = _metrics_thread = _metrics_addr = None
    if httpd is not None:
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(5.0)


def metrics_server_address() -> Optional[Tuple[str, int]]:
    return _metrics_addr


def _on_metrics_port(port: Optional[str]) -> None:
    """telemetry's env-refresh hook for ``MXNET_METRICS_PORT``."""
    if not port:
        stop_metrics_server()
        return
    try:
        p = int(port)
    except ValueError:
        _logger().warning("invalid MXNET_METRICS_PORT=%r (want an int)",
                          port)
        return
    if _metrics_httpd is None:
        addr = start_metrics_server(p)
        _logger().info("metrics exporter serving /metrics on %s:%d",
                       *addr)
