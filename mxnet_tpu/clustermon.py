"""Cluster-scope observability: rank-aware aggregation + Prometheus.

Telemetry (telemetry.py) and the flight recorder (tracing.py) are
strictly single-process; once the commit barrier (checkpoint.py) makes
multi-host the default failure domain, the first-order question stops
being "is this step slow" and becomes "WHICH rank made it slow, and
why".  This module is that layer, built on the same file-based rank
coordination the checkpoint barrier already proved out:

- **Spools**: with ``MXNET_CLUSTER_DIR`` set, every rank appends its
  per-step telemetry record (stamped ``rank``/``world`` — resolved
  through the checkpoint ``set_rank`` precedence chain, plus a
  thread-local override for threads-as-ranks harnesses) to
  ``<dir>/rank-<r>.jsonl``.  One JSON object per line, flushed per
  record, so a live cluster can be tailed from any host that mounts
  the shared directory.
- **Aggregator** (rank 0 only): a daemon thread tails all spools,
  joins records by per-rank step ordinal, and produces a cluster view:
  per-rank step-time skew, barrier-wait asymmetry, and a per-step
  critical-path decomposition (input wait / H2D / compile / collective
  / optimizer update / checkpoint) derived from tracing-span bucket
  deltas where tracing is live and record fields where it is not.
- **Straggler detector**: over a sliding window
  (``MXNET_CLUSTER_WINDOW`` joined steps) the slowest rank is named
  when its mean step time exceeds ``MXNET_STRAGGLER_FACTOR`` × the
  median of its peers, and its dominant cause is classified
  (``input_bound`` / ``compile_stall`` / ``ckpt_interference`` /
  ``comm_skew``) from the per-signal excess over the peer median.
  Results land in the ``cluster.straggler_rank`` /
  ``cluster.straggler_cause`` gauges with ONE log line per incident
  (re-logged only when the rank or cause changes).
- **Prometheus**: :func:`prometheus_text` renders the whole telemetry
  registry in text exposition format (``# TYPE`` lines, ``rank=""``
  label on every sample, histograms as summaries with reservoir
  quantiles).  ``GET /metrics`` on the serving server and a standalone
  ``MXNET_METRICS_PORT`` exporter for training runs serve it.
- **Incidents** (phase 2): every straggler detection opens an incident
  record (rank, cause, start/end ``rank_step``, peak skew, duration)
  in :class:`IncidentStore` — a bounded ring
  (``MXNET_CLUSTER_HISTORY``) persisted as ``incidents.jsonl`` next to
  the spools, closed out when the detector clears, and exposed as the
  ``cluster.incidents_total{cause=...}`` Prometheus counter family
  plus a ``GET /incidents`` JSON route on both scrape surfaces.
- **Spool lifecycle**: with ``MXNET_CLUSTER_SPOOL_MAX_MB`` set the
  sink rotates ``rank-<r>.jsonl`` into numbered segments
  (``rank-<r>.jsonl.<k>``), keeps the newest
  ``MXNET_CLUSTER_SPOOL_KEEP`` and compacts retired segments into
  per-window summary records (``rank-<r>.summary.jsonl``) so week-long
  runs stay bounded on disk yet post-mortem-queryable.  The rank-0
  tailer follows rotations byte-exactly, carrying torn lines across
  segment boundaries.
- **Remediation hooks**: :func:`on_incident` callbacks fire from the
  aggregator thread (never the step path) on incident open / escalate
  / close; :func:`rank_health` gives the elastic restore barrier a
  healthy / degraded(cause) / missing view (a rank whose spool stops
  advancing for ``MXNET_CLUSTER_RANK_TIMEOUT_S`` is demoted from the
  live-rank join set and re-admitted when its spool resumes); a
  persistently ``input_bound`` incident publishes a prefetch-depth
  advice record the straggling rank applies under ``MXNET_REMEDIATE=1``
  (logged + counted either way).

Disabled contract: with ``MXNET_CLUSTER_DIR`` and
``MXNET_METRICS_PORT`` unset nothing here runs — no spool files, no
aggregator or exporter thread, and the step path is bitwise identical
to the pre-clustermon build (telemetry's ``begin_step`` fast path is
untouched).  ``tools/cluster_report.py`` replays the same join +
detection over spools offline for post-mortems.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import telemetry

__all__ = ["rank_world", "set_thread_rank", "note_rank", "SpoolSink",
           "ClusterAggregator", "aggregator", "cluster_view",
           "join_by_step", "window_stats", "detect_straggler",
           "record_signals", "CAUSES", "SERVING_CAUSES",
           "IncidentStore", "incident_view", "on_incident",
           "remove_incident_hook", "incident_hooks",
           "register_incident_store", "unregister_incident_store",
           "rank_health",
           "prometheus_text", "parse_prometheus_text",
           "start_metrics_server", "stop_metrics_server",
           "metrics_server_address"]

_LOCK = threading.Lock()

_SPOOL_RE = re.compile(r"rank-(\d+)\.jsonl$")
_SEG_RE = re.compile(r"rank-(\d+)\.jsonl\.(\d+)$")
# sort key for the live (unnumbered) spool file: after every segment
_LIVE = float("inf")

INCIDENT_FILE = "incidents.jsonl"
ADVICE_FILE = "advice.jsonl"

# cluster-health metrics (created eagerly so profiler.counters() and a
# /metrics scrape always see the keys, zeros/none before the first
# aggregator pass)
_G_RANKS = telemetry.gauge("cluster.ranks")
_G_LIVE_RANKS = telemetry.gauge("cluster.live_ranks")
_G_SKEW = telemetry.gauge("cluster.step_ms_skew")
_G_BARRIER_SKEW = telemetry.gauge("cluster.barrier_wait_skew_ms")
_G_STRAGGLER = telemetry.gauge("cluster.straggler_rank")
_G_CAUSE = telemetry.gauge("cluster.straggler_cause")
# which mesh axis a comm_skew straggler is skewed on ("dp"/"tp"/...,
# "none" otherwise).  A detail gauge, NOT part of the cause string —
# the incidents_total{cause=...} family stays at bounded cardinality
_G_COMM_AXIS = telemetry.gauge("cluster.straggler_comm_axis")
_C_INCIDENTS = telemetry.counter("cluster.straggler_incidents")
_C_JOINED = telemetry.counter("cluster.joined_steps")
_C_ROTATIONS = telemetry.counter("cluster.spool_rotations")
_C_LOST_SEGMENTS = telemetry.counter("cluster.spool_lost_segments")
_C_ADVICE_PUB = telemetry.counter("cluster.advice_published")
_C_ADVICE_APPLIED = telemetry.counter("cluster.advice_applied")
_C_ADVICE_IGNORED = telemetry.counter("cluster.advice_ignored")

# per-cause incident counters; prometheus_text() folds the
# "cluster.incidents_total.<cause>" names into ONE
# mxnet_cluster_incidents_total{cause="<cause>"} counter family
_INCIDENTS_FAMILY = "cluster.incidents_total."
_C_INCIDENT_CAUSE = {
    c: telemetry.counter(_INCIDENTS_FAMILY + c)
    for c in ("input_bound", "compile_stall", "ckpt_interference",
              "comm_skew", "latency_slo", "error_budget",
              "queue_saturation", "ttft_slo", "unknown")}

# string-gauge values ever rendered, per metric — the stale-series fix:
# a scrape emits the CURRENT value at 1 and every previously-seen value
# at 0 so Prometheus alert rules don't latch onto a cleared cause
_STR_SEEN: Dict[str, set] = {}


def _logger():
    from .log import get_logger
    return get_logger("mxnet_tpu.clustermon")


# -- rank/world resolution ---------------------------------------------------
# Precedence: per-thread override (threads-as-ranks harnesses) > the
# checkpoint chain (explicit env > DistKVStore's set_rank plumbing >
# jax.process_index()).  The checkpoint resolution is cached keyed on
# the inputs it depends on, so per-span stamping never pays a backend
# call.

_tls = threading.local()
_rank_cache: Dict[str, Any] = {"key": None, "rw": (0, 1)}


def set_thread_rank(rank: Optional[int], world: int = 1) -> None:
    """Pin (rank, world) for the CALLING thread only — how a
    threads-as-ranks harness gives each worker thread its own spool.
    ``None`` clears the override."""
    if rank is None:
        _tls.rw = None
    else:
        _tls.rw = (int(rank), max(1, int(world)))


def note_rank(rank: int, world: int) -> None:
    """Invalidate the cached process-level resolution (called by the
    dist kvstore right after ``checkpoint.set_rank`` so the next record
    picks the plumbed identity up immediately)."""
    with _LOCK:
        _rank_cache["key"] = None


def rank_world() -> Tuple[int, int]:
    """(rank, world) for stamping records and spans."""
    rw = getattr(_tls, "rw", None)
    if rw is not None:
        return rw
    return _process_rank_world()


def _process_rank_world() -> Tuple[int, int]:
    """The checkpoint-chain resolution only (no thread-local override)
    — what decides which PROCESS hosts the aggregator."""
    from . import checkpoint
    key = (os.environ.get("MXNET_CKPT_RANK"),
           os.environ.get("MXNET_CKPT_WORLD"),
           checkpoint._rank_override)
    with _LOCK:
        if key == _rank_cache["key"]:
            return _rank_cache["rw"]
    try:
        rw = checkpoint.rank_world()
    except Exception:
        rw = (0, 1)     # invalid env raises at save() where it matters
    with _LOCK:
        _rank_cache["key"] = key
        _rank_cache["rw"] = rw
    return rw


# -- per-rank spools ---------------------------------------------------------

def _spool_max_bytes() -> int:
    """Rotation threshold from ``MXNET_CLUSTER_SPOOL_MAX_MB`` (float MB
    so tests can force rotation with sub-MB spools); 0/unset disables
    rotation — the pre-lifecycle single-file behavior."""
    v = os.environ.get("MXNET_CLUSTER_SPOOL_MAX_MB")
    try:
        return max(0, int(float(v) * 1024 * 1024)) if v else 0
    except ValueError:
        return 0


def _spool_keep() -> int:
    """Segments retained per rank (``MXNET_CLUSTER_SPOOL_KEEP``,
    default 4; 0 = retain all — the checkpoint_gc keep-N idiom).  Older
    segments are compacted into summary records, then removed."""
    v = os.environ.get("MXNET_CLUSTER_SPOOL_KEEP")
    try:
        return max(0, int(v)) if v else 4
    except ValueError:
        return 4


def _history_keep() -> int:
    """Closed incidents retained in the in-memory ring
    (``MXNET_CLUSTER_HISTORY``, default 256)."""
    v = os.environ.get("MXNET_CLUSTER_HISTORY")
    try:
        return max(1, int(v)) if v else 256
    except ValueError:
        return 256


def _rank_timeout_s() -> float:
    """Seconds of spool silence before a rank is demoted from the live
    join set (``MXNET_CLUSTER_RANK_TIMEOUT_S``; 0/unset = never)."""
    v = os.environ.get("MXNET_CLUSTER_RANK_TIMEOUT_S")
    try:
        return max(0.0, float(v)) if v else 0.0
    except ValueError:
        return 0.0


def _remediate_enabled() -> bool:
    return os.environ.get("MXNET_REMEDIATE") == "1"


class SpoolSink:
    """Telemetry sink appending each step record to the emitting rank's
    spool (``<dir>/rank-<r>.jsonl``).  A ``rank_step`` ordinal (this
    rank's Nth record) is stamped so the aggregator can join steps
    across ranks even when the process-global ``step`` counter
    interleaves (threads-as-ranks).

    Lifecycle: when a spool would exceed ``max_bytes``
    (``MXNET_CLUSTER_SPOOL_MAX_MB``) it rotates to the next numbered
    segment ``rank-<r>.jsonl.<k>`` — records never straddle the
    threshold mid-line, so every segment ends on a record boundary from
    the WRITER's side (the tailer still handles torn lines from crashed
    writers).  Only the newest ``keep`` segments are retained; older
    ones are folded into per-window summary records in
    ``rank-<r>.summary.jsonl`` before removal, so a week-long run stays
    bounded on disk but remains post-mortem-queryable.

    The sink is also the rank-side consumer of the aggregator's
    remediation advice (``advice.jsonl``): every few records it drains
    new advice lines addressed to a rank this process emits for, and
    applies them (``MXNET_REMEDIATE=1``) or logs+counts them as
    advisory."""

    def __init__(self, directory: str, max_bytes: Optional[int] = None,
                 keep: Optional[int] = None,
                 rotate_age_s: Optional[float] = None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.max_bytes = (_spool_max_bytes() if max_bytes is None
                          else max(0, int(max_bytes)))
        self.keep = _spool_keep() if keep is None else max(0, int(keep))
        self.rotate_age_s = rotate_age_s
        self._files: Dict[int, Any] = {}
        self._counts: Dict[int, int] = {}
        self._sizes: Dict[int, int] = {}
        self._opened: Dict[int, float] = {}
        self._seg_next: Dict[int, int] = {}
        self._advice_off = 0
        self._lock = threading.Lock()

    def _path(self, r: int) -> str:
        return os.path.join(self.directory, f"rank-{r}.jsonl")

    def emit(self, record: dict) -> None:
        r = int(record.get("rank", 0))
        with self._lock:
            n = self._counts.get(r, 0) + 1
            self._counts[r] = n
            line = json.dumps(dict(record, rank_step=n)) + "\n"
            now = time.monotonic()
            f = self._files.get(r)
            if f is not None and self._should_rotate(r, len(line), now):
                self._rotate(r)
                f = None
            if f is None:
                path = self._path(r)
                f = self._files[r] = open(path, "a", buffering=1)
                try:
                    self._sizes[r] = os.path.getsize(path)
                except OSError:
                    self._sizes[r] = 0
                self._opened[r] = now
            f.write(line)
            self._sizes[r] = self._sizes.get(r, 0) + len(line)
            if n % 4 == 0:
                self._consume_advice()

    # -- rotation / compaction -----------------------------------------------

    def _should_rotate(self, r: int, nbytes: int, now: float) -> bool:
        size = self._sizes.get(r, 0)
        if size <= 0:       # never rotate an empty spool
            return False
        if self.max_bytes and size + nbytes > self.max_bytes:
            return True
        return (self.rotate_age_s is not None
                and now - self._opened.get(r, now) >= self.rotate_age_s)

    def _rotate(self, r: int) -> None:
        f = self._files.pop(r, None)
        if f is not None:
            try:
                f.close()
            except Exception:
                pass
        path = self._path(r)
        k = self._seg_next.get(r)
        if k is None:       # resume numbering after a restart
            ks = [int(m.group(2)) for m in
                  (_SEG_RE.match(nm) for nm in os.listdir(self.directory))
                  if m and int(m.group(1)) == r]
            k = max(ks, default=0) + 1
        try:
            os.rename(path, f"{path}.{k}")
        except OSError:
            return          # keep appending to the live file
        self._seg_next[r] = k + 1
        self._sizes[r] = 0
        _C_ROTATIONS.inc()
        if self.keep:
            self._prune(r)

    def _prune(self, r: int) -> None:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        segs = []
        for nm in names:
            m = _SEG_RE.match(nm)
            if m and int(m.group(1)) == r:
                segs.append((int(m.group(2)), nm))
        segs.sort()
        while len(segs) > self.keep:
            k, nm = segs.pop(0)
            path = os.path.join(self.directory, nm)
            try:
                self._compact(r, path, k)
            except Exception:
                _logger().exception("spool compaction failed for %s", nm)
            try:
                os.remove(path)
            except OSError:
                pass

    def _compact(self, r: int, seg_path: str, k: int) -> None:
        """Fold a retired segment into per-window summary records —
        same window size the detector uses, so offline reports can
        reconcile compacted history with live totals."""
        recs = []
        with open(seg_path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    recs.append(json.loads(ln))
                except ValueError:
                    continue
        if not recs:
            return
        window = max(1, _cluster_window())
        out = os.path.join(self.directory, f"rank-{r}.summary.jsonl")
        with open(out, "a") as f:
            for i in range(0, len(recs), window):
                chunk = recs[i:i + window]
                host = [float(x.get("host_ms") or 0.0) for x in chunk]
                sigs = [record_signals(x) for x in chunk]
                f.write(json.dumps({
                    "summary": True, "rank": r, "segment": k,
                    "rank_step_first": int(chunk[0].get("rank_step")
                                           or 0),
                    "rank_step_last": int(chunk[-1].get("rank_step")
                                          or 0),
                    "steps": len(chunk),
                    "host_ms_mean": round(_mean(host), 3),
                    "host_ms_max": round(max(host, default=0.0), 3),
                    "host_ms_total": round(sum(host), 3),
                    "signals": {
                        kk: round(_mean([s[kk] for s in sigs]), 3)
                        for kk in ("input", "compile", "checkpoint",
                                   "comm")},
                    "ts_first": chunk[0].get("ts"),
                    "ts_last": chunk[-1].get("ts"),
                }) + "\n")

    # -- remediation advice (rank side) --------------------------------------

    def _consume_advice(self) -> None:
        """Drain new complete lines from ``advice.jsonl`` (published by
        the rank-0 aggregator) and act on advice addressed to a rank
        this process emits for.  Called from ``emit`` under the sink
        lock, every 4th record per rank — one stat() amortized over
        steps, never on the critical path of other ranks."""
        path = os.path.join(self.directory, ADVICE_FILE)
        try:
            if os.path.getsize(path) <= self._advice_off:
                return
            with open(path, "rb") as f:
                f.seek(self._advice_off)
                data = f.read()
        except OSError:
            return
        nl = data.rfind(b"\n")
        if nl < 0:
            return          # torn write; retry next time
        data = data[:nl + 1]
        self._advice_off += len(data)
        for ln in data.decode("utf-8", "replace").splitlines():
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get("action") != "prefetch_depth":
                continue
            try:
                target = int(rec.get("rank", -1))
                depth = int(rec.get("depth") or 0)
            except (TypeError, ValueError):
                continue
            if target not in self._counts or depth <= 0:
                continue    # addressed to a rank outside this process
            if _remediate_enabled():
                from .data.device_pipeline import note_advice_depth
                note_advice_depth(depth)
                _C_ADVICE_APPLIED.inc()
                _logger().warning(
                    "remediation applied for rank %d (incident %s): "
                    "DevicePrefetcher depth -> %d at the next epoch",
                    target, rec.get("incident_id"), depth)
            else:
                _C_ADVICE_IGNORED.inc()
                _logger().warning(
                    "remediation advice for rank %d (incident %s) "
                    "ignored: DevicePrefetcher depth -> %d; set "
                    "MXNET_REMEDIATE=1 to apply",
                    target, rec.get("incident_id"), depth)

    def close(self) -> None:
        with self._lock:
            for f in self._files.values():
                try:
                    f.close()
                except Exception:
                    pass
            self._files.clear()


# -- record signal extraction ------------------------------------------------

# straggler cause classes, in the order the ARCHITECTURE decision-rule
# table documents them
CAUSES = ("input_bound", "compile_stall", "ckpt_interference",
          "comm_skew")

# serving-side incident causes (serving/slo.py burn-rate alerting);
# same IncidentStore state machine and incidents_total counter family
SERVING_CAUSES = ("latency_slo", "error_budget", "queue_saturation",
                  "ttft_slo")

_SIG_OF_CAUSE = {"input_bound": "input", "compile_stall": "compile",
                 "ckpt_interference": "checkpoint", "comm_skew": "comm"}
_CAUSE_OF_SIG = {v: k for k, v in _SIG_OF_CAUSE.items()}


def record_signals(rec: dict) -> Dict[str, float]:
    """Per-record attribution signals (ms) for the straggler
    classifier.  Span-bucket deltas (``critical_path``, present when
    tracing is live) and record fields measure overlapping intervals —
    ``max`` of the two is taken per signal rather than their sum so a
    traced run never double-counts."""
    cp = rec.get("critical_path") or {}
    ck = rec.get("checkpoint") or {}
    return {
        "input": max(float(rec.get("input_wait_ms") or 0.0),
                     float(cp.get("input_wait") or 0.0)),
        "compile": max(float(rec.get("compile_ms") or 0.0),
                       float(cp.get("compile") or 0.0)),
        "checkpoint": max(float(ck.get("barrier_wait_ms") or 0.0),
                          float(cp.get("checkpoint") or 0.0)),
        "comm": float(cp.get("collective") or 0.0),
    }


def join_by_step(by_rank: Dict[int, List[dict]]) -> Dict[int, Dict[int,
                                                                   dict]]:
    """Join records across ranks: {step: {rank: record}}.  The join key
    is the per-rank ``rank_step`` ordinal the spool sink stamps (the
    i-th record a rank emitted IS its i-th step), falling back to
    position for spools that predate the field."""
    joined: Dict[int, Dict[int, dict]] = {}
    for r, recs in by_rank.items():
        for i, rec in enumerate(recs):
            step = int(rec.get("rank_step", i + 1))
            joined.setdefault(step, {})[r] = rec
    return joined


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _median(vals: List[float]) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def window_stats(by_rank: Dict[int, List[dict]], window: int,
                 live_ranks: Optional[List[int]] = None
                 ) -> Dict[int, dict]:
    """Per-rank aggregates over the trailing ``window`` JOINED steps
    (only steps every rank has reported — a rank that is behind must
    not look fast because its slow steps haven't landed yet).  When
    ``live_ranks`` is given, completeness and the stats cover only
    those ranks — how the aggregator keeps joining after a dead rank
    is demoted; offline callers omit it and get every rank."""
    joined = join_by_step(by_rank)
    ranks = (sorted(live_ranks) if live_ranks is not None
             else sorted(by_rank))
    complete = sorted(s for s, per in joined.items()
                      if all(r in per for r in ranks))
    tail = complete[-window:] if window else complete
    stats: Dict[int, dict] = {}
    for r in ranks:
        recs = [joined[s][r] for s in tail]
        host = [float(x.get("host_ms") or 0.0) for x in recs]
        sigs = [record_signals(x) for x in recs]
        cps = [x.get("critical_path") or {} for x in recs]
        axs = [(x.get("collective_split") or {}).get("by_axis") or {}
               for x in recs]
        stats[r] = {
            "steps": len(recs),
            "host_ms_mean": _mean(host),
            "host_ms_max": max(host, default=0.0),
            "signals": {k: _mean([s[k] for s in sigs])
                        for k in ("input", "compile", "checkpoint",
                                  "comm")},
            "critical_path": {
                k: _mean([float(c.get(k) or 0.0) for c in cps])
                for k in ("input_wait", "h2d", "compile", "collective",
                          "optimizer", "checkpoint", "compute")},
            # mean modeled collective bytes per mesh axis
            # (collective_split.by_axis) — lets a comm_skew verdict
            # name WHICH axis (dp grad sync vs tp activation
            # all-reduce vs ep all_to_all) carries the skew
            "comm_axis_bytes": {
                ax: _mean([float(a.get(ax) or 0.0) for a in axs])
                for ax in telemetry.MESH_AXES},
            "barrier_wait_ms_mean": _mean(
                [float((x.get("checkpoint") or {})
                       .get("barrier_wait_ms") or 0.0) for x in recs]),
        }
    return stats


def detect_straggler(stats: Dict[int, dict],
                     factor: float) -> Optional[dict]:
    """Name the slowest rank in a window and classify its dominant
    cause.  Decision rule (docs/ARCHITECTURE.md "Cluster
    observability"): the slowest rank is a straggler when its mean
    step time exceeds ``factor`` × the median of the OTHER ranks';
    its cause is the signal with the largest excess over the peer
    median, or ``unknown`` when no signal explains ≥10% of the step
    -time excess (unattributed compute — a thermally-throttled chip
    looks like this)."""
    live = {r: s for r, s in stats.items() if s["steps"]}
    if len(live) < 2:
        return None
    slowest = max(live, key=lambda r: live[r]["host_ms_mean"])
    peers = [live[r]["host_ms_mean"] for r in live if r != slowest]
    med = _median(peers)
    mean = live[slowest]["host_ms_mean"]
    if med <= 0.0 or mean <= factor * med:
        return None
    excess = {
        sig: live[slowest]["signals"][sig]
        - _median([live[r]["signals"][sig] for r in live if r != slowest])
        for sig in ("input", "compile", "checkpoint", "comm")}
    total_excess = mean - med
    top = max(excess, key=lambda k: excess[k])
    if excess[top] <= 0.0 or excess[top] < 0.1 * total_excess:
        cause = "unknown"
    else:
        cause = _CAUSE_OF_SIG[top]
    # mesh-axis attribution for comm_skew: the axis whose modeled
    # byte volume on the straggler most exceeds the peer median.  A
    # DETAIL field beside the cause — the cause string (and the
    # incidents_total counter family) stays "comm_skew" so Prometheus
    # cardinality is unchanged.
    comm_axis = None
    if cause == "comm_skew":
        ax_excess = {}
        for ax in telemetry.MESH_AXES:
            mine = live[slowest].get("comm_axis_bytes", {}).get(ax, 0.0)
            peer = _median([live[r].get("comm_axis_bytes", {})
                            .get(ax, 0.0) for r in live if r != slowest])
            ax_excess[ax] = mine - peer
        best = max(ax_excess, key=lambda a: ax_excess[a])
        if ax_excess[best] > 0.0:
            comm_axis = best
        elif live[slowest].get("comm_axis_bytes"):
            # symmetric volumes — fall back to the heaviest axis on
            # the straggler itself so operators still get a name
            vols = live[slowest]["comm_axis_bytes"]
            heaviest = max(vols, key=lambda a: vols[a])
            comm_axis = heaviest if vols[heaviest] > 0.0 else None
    return {"rank": slowest, "cause": cause, "comm_axis": comm_axis,
            "ratio": mean / med, "step_ms": mean, "peer_ms": med,
            "excess_ms": {_CAUSE_OF_SIG[k]: round(v, 3)
                          for k, v in excess.items()}}


# -- incident store ----------------------------------------------------------

# an open incident "escalates" — hooks see the transition and the
# built-in remediation publishes advice — only after the detector has
# confirmed it on this many recomputes, so one flapping window never
# drives action
ESCALATE_POLLS = 2


class IncidentStore:
    """Bounded incident history for the rank-0 aggregator.

    At most one incident is open at a time (the detector names at most
    one straggler); :meth:`observe` advances the state machine on every
    detector verdict and returns the lifecycle events
    (``open`` / ``escalate`` / ``close``) that transition produced, so
    the caller can bump counters and fire hooks exactly once per
    transition.  Every transition is also appended to
    ``<dir>/incidents.jsonl`` for post-mortems; closed incidents stay
    in a ring of ``MXNET_CLUSTER_HISTORY`` entries for ``/incidents``.

    Not internally locked — only ever touched under the aggregator's
    lock."""

    def __init__(self, directory: Optional[str] = None,
                 keep: Optional[int] = None):
        self.directory = directory
        self.keep = _history_keep() if keep is None else max(1, int(keep))
        self._next_id = 1
        self._open: Optional[dict] = None
        self._closed: List[dict] = []
        self._counts: Dict[str, int] = {}

    def observe(self, straggler: Optional[dict], step: int,
                now: float) -> List[dict]:
        """One detector verdict in; lifecycle events out.  ``step`` is
        the latest fully-joined step (the incident's timeline unit) and
        ``now`` a wall-clock timestamp."""
        events: List[dict] = []
        cur = self._open
        if straggler is None:
            if cur is not None:
                events.append(self._close(cur, step, now))
            return events
        rank, cause = int(straggler["rank"]), straggler["cause"]
        if cur is not None and (cur["rank"] != rank
                                or cur["cause"] != cause):
            events.append(self._close(cur, step, now))
            cur = None
        if cur is None:
            cur = self._open = {
                "id": self._next_id, "status": "open",
                "rank": rank, "cause": cause,
                "comm_axis": straggler.get("comm_axis"),
                "start_rank_step": int(step), "end_rank_step": None,
                "start_ts": round(now, 3), "end_ts": None,
                "duration_s": None,
                "peak_ratio": round(float(straggler["ratio"]), 3),
                "peak_step_ms": round(float(straggler["step_ms"]), 3),
                "polls": 1, "escalated": False,
            }
            self._next_id += 1
            self._counts[cause] = self._counts.get(cause, 0) + 1
            self._persist("open", cur)
            events.append({"event": "open", "incident": dict(cur)})
            return events
        cur["polls"] += 1
        if straggler.get("comm_axis"):
            cur["comm_axis"] = straggler["comm_axis"]
        cur["peak_ratio"] = round(max(cur["peak_ratio"],
                                      float(straggler["ratio"])), 3)
        cur["peak_step_ms"] = round(max(cur["peak_step_ms"],
                                        float(straggler["step_ms"])), 3)
        if not cur["escalated"] and cur["polls"] >= ESCALATE_POLLS:
            cur["escalated"] = True
            self._persist("escalate", cur)
            events.append({"event": "escalate", "incident": dict(cur)})
        return events

    def _close(self, inc: dict, step: int, now: float) -> dict:
        inc["status"] = "closed"
        inc["end_rank_step"] = int(step)
        inc["end_ts"] = round(now, 3)
        inc["duration_s"] = round(max(0.0, now - inc["start_ts"]), 3)
        self._open = None
        self._closed.append(inc)
        if len(self._closed) > self.keep:
            del self._closed[:len(self._closed) - self.keep]
        self._persist("close", inc)
        return {"event": "close", "incident": dict(inc)}

    def _persist(self, event: str, inc: dict) -> None:
        if not self.directory:
            return
        try:
            with open(os.path.join(self.directory, INCIDENT_FILE),
                      "a") as f:
                f.write(json.dumps(dict(inc, event=event)) + "\n")
        except OSError:
            pass            # history is best-effort; detection is not

    def snapshot(self, limit: int = 50) -> dict:
        return {"open": [dict(self._open)] if self._open else [],
                "recent": [dict(i) for i in self._closed[-limit:]],
                "counts": dict(self._counts)}


# -- remediation hook plane --------------------------------------------------

_HOOKS: List[Any] = []


def on_incident(fn) -> Any:
    """Register ``fn(event, incident)`` to fire on incident lifecycle
    transitions (``event`` is ``"open"`` / ``"escalate"`` /
    ``"close"``; ``incident`` is a copy of the record).  Hooks run on
    the rank-0 aggregator's poll thread — never the step path — at
    most once per transition; an exception is logged and swallowed.
    Returns ``fn`` so it can decorate."""
    with _LOCK:
        if fn not in _HOOKS:
            _HOOKS.append(fn)
    return fn


def remove_incident_hook(fn) -> None:
    with _LOCK:
        if fn in _HOOKS:
            _HOOKS.remove(fn)


def incident_hooks() -> List[Any]:
    """The registered on_incident hooks (a copy) — so out-of-aggregator
    incident producers (serving/slo.py) fire the same hook plane."""
    with _LOCK:
        return list(_HOOKS)


# extra incident stores merged into incident_view(): anything with a
# ``snapshot(limit)`` returning the IncidentStore shape (open / recent /
# counts).  serving/slo.py registers its engine here so GET /incidents
# shows serving incidents beside straggler incidents.
_EXTRA_STORES: List[Any] = []


def register_incident_store(store) -> Any:
    with _LOCK:
        if store not in _EXTRA_STORES:
            _EXTRA_STORES.append(store)
    return store


def unregister_incident_store(store) -> None:
    with _LOCK:
        if store in _EXTRA_STORES:
            _EXTRA_STORES.remove(store)


# -- the rank-0 aggregator ---------------------------------------------------

def _straggler_factor() -> float:
    v = os.environ.get("MXNET_STRAGGLER_FACTOR")
    try:
        return max(1.0, float(v)) if v else 1.5
    except ValueError:
        return 1.5


def _cluster_window() -> int:
    v = os.environ.get("MXNET_CLUSTER_WINDOW")
    try:
        return max(1, int(v)) if v else 20
    except ValueError:
        return 20


class ClusterAggregator:
    """Tails every ``rank-*.jsonl`` spool in ``directory``, joins
    records by step, and maintains the cluster view + gauges.  Owns an
    optional daemon thread (:meth:`start`); :meth:`poll` runs one pass
    synchronously so tests and the report tool stay deterministic."""

    def __init__(self, directory: str, window: Optional[int] = None,
                 factor: Optional[float] = None, poll_s: float = 0.5,
                 keep: int = 512,
                 rank_timeout_s: Optional[float] = None,
                 history: Optional[int] = None):
        self.directory = directory
        self.window = window if window is not None else _cluster_window()
        self.factor = factor if factor is not None else _straggler_factor()
        self.poll_s = max(0.05, float(poll_s))
        self.keep = max(self.window * 4, keep)
        self.rank_timeout_s = (_rank_timeout_s() if rank_timeout_s is None
                               else max(0.0, float(rank_timeout_s)))
        self.incidents = IncidentStore(directory, keep=history)
        self._by_rank: Dict[int, List[dict]] = {}
        # per-rank tail state: highest fully-read segment number, byte
        # offset + torn-line buffer into the file after it
        self._rstate: Dict[int, dict] = {}
        self._last_seen: Dict[int, float] = {}
        self._last_step: Dict[int, int] = {}
        self._health: Dict[int, dict] = {}
        self._missing: set = set()
        self._pending: List[dict] = []
        self._view: dict = {"ranks": {}, "straggler": None, "skew": None,
                            "window": self.window, "joined_steps": 0,
                            "live_ranks": [], "missing_ranks": [],
                            "health": {}}
        self._joined_seen = 0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # -- spool tailing -------------------------------------------------------

    def _read_new(self) -> bool:
        """Drain complete new lines from every spool; True when any
        record arrived.  Offsets are byte-exact, a partial trailing
        line (a rank mid-write) is buffered until its newline lands,
        and the buffer is carried ACROSS segment boundaries so a
        rotation mid-read never loses the torn record.  Per rank the
        files form one logical stream: segments ``rank-<r>.jsonl.<k>``
        in ``k`` order, then the live ``rank-<r>.jsonl``."""
        grew = False
        try:
            names = os.listdir(self.directory)
        except OSError:
            return False
        per_rank: Dict[int, Dict[int, str]] = {}
        for name in names:
            m = _SPOOL_RE.match(name)
            if m:       # the live file reads after every segment
                per_rank.setdefault(int(m.group(1)), {})[_LIVE] = name
                continue
            m = _SEG_RE.match(name)
            if m:
                per_rank.setdefault(int(m.group(1)),
                                    {})[int(m.group(2))] = name
        now = time.monotonic()
        for rank in sorted(per_rank):
            st = self._rstate.get(rank)
            if st is None:
                st = self._rstate[rank] = {"seg_done": 0, "off": 0,
                                           "buf": b""}
                self._last_seen.setdefault(rank, now)
            todo = sorted(k for k in per_rank[rank]
                          if k > st["seg_done"])
            if not todo:
                continue
            if todo[0] != _LIVE and todo[0] > st["seg_done"] + 1:
                # older segments were pruned before we read them
                lost = todo[0] - st["seg_done"] - 1
                _C_LOST_SEGMENTS.inc(lost)
                _logger().warning(
                    "rank %d: %d spool segment(s) pruned before the "
                    "aggregator read them (raise "
                    "MXNET_CLUSTER_SPOOL_KEEP)", rank, lost)
                st["off"], st["buf"] = 0, b""
            recs = self._by_rank.setdefault(rank, [])
            added = False
            for j, k in enumerate(todo):
                path = os.path.join(self.directory, per_rank[rank][k])
                off = st["off"] if j == 0 else 0
                try:
                    with open(path, "rb") as f:
                        f.seek(off)
                        data = f.read()
                except OSError:
                    continue    # pruned mid-pass; next poll resyncs
                off += len(data)
                buf = st["buf"] + data
                *lines, buf = buf.split(b"\n")
                st["off"], st["buf"] = off, buf
                for ln in lines:
                    if not ln.strip():
                        continue
                    try:
                        recs.append(json.loads(ln))
                        added = True
                    except ValueError:
                        continue        # torn write; skip the line
                if k != _LIVE:
                    # segment fully consumed; the next file starts at 0
                    # with the torn tail (if any) carried forward
                    st["seg_done"], st["off"] = k, 0
            if added:
                grew = True
                self._last_seen[rank] = now
                if len(recs) > self.keep:
                    del recs[:len(recs) - self.keep]
                self._last_step[rank] = int(
                    recs[-1].get("rank_step", len(recs)))
        return grew

    # -- view / gauges -------------------------------------------------------

    def poll(self) -> dict:
        """One synchronous pass: tail spools, recompute the view,
        refresh gauges, log incident transitions, then fire
        ``on_incident`` hooks OUTSIDE the lock.  Returns the view."""
        with self._lock:
            grew = self._read_new()
            if grew or not self._view["ranks"] \
                    or self.rank_timeout_s > 0:
                self._recompute()
            view = dict(self._view)
            events, self._pending = self._pending, []
        for ev in events:
            self._dispatch(ev)
        return view

    def _recompute(self) -> None:
        now = time.monotonic()
        all_ranks = sorted(self._by_rank)
        timeout = self.rank_timeout_s
        live = [r for r in all_ranks if not timeout
                or now - self._last_seen.get(r, now) < timeout]
        missing = [r for r in all_ranks if r not in set(live)]
        for r in missing:
            if r not in self._missing:
                self._missing.add(r)
                _logger().warning(
                    "rank %d demoted from the live set: no spool "
                    "records for %.1fs (> MXNET_CLUSTER_RANK_TIMEOUT_S"
                    "=%.1fs); joining on survivors", r,
                    now - self._last_seen.get(r, now), timeout)
        for r in list(self._missing):
            if r in set(live):
                self._missing.discard(r)
                _logger().info("rank %d re-admitted to the live set: "
                               "spool resumed", r)
        stats = window_stats(self._by_rank, self.window,
                             live_ranks=live)
        straggler = detect_straggler(stats, self.factor)
        means = [s["host_ms_mean"] for s in stats.values() if s["steps"]]
        barrier = [s["barrier_wait_ms_mean"] for s in stats.values()
                   if s["steps"]]
        joined = join_by_step(self._by_rank)
        complete_steps = sorted(
            s for s, per in joined.items()
            if all(r in per for r in live)) if live else []
        complete = len(complete_steps)
        skew = None
        if len(means) >= 2:
            skew = {"step_ms": max(means) - min(means),
                    "step_ratio": max(means) / min(means)
                    if min(means) > 0 else None,
                    "barrier_wait_ms": max(barrier) - min(barrier)}
        # incident lifecycle: one verdict in, transitions out
        last_step = complete_steps[-1] if complete_steps else 0
        events = self.incidents.observe(straggler, last_step,
                                        time.time())
        open_inc = self.incidents._open
        health = {}
        for r in all_ranks:
            entry = {"status": "healthy", "cause": None,
                     "last_rank_step": self._last_step.get(r, 0),
                     "since_s": round(now - self._last_seen.get(r, now),
                                      3)}
            if r in self._missing:
                entry["status"] = "missing"
            elif open_inc is not None and open_inc["rank"] == r:
                entry["status"] = "degraded"
                entry["cause"] = open_inc["cause"]
            health[r] = entry
        self._health = health
        self._view = {"ranks": stats, "straggler": straggler,
                      "skew": skew, "window": self.window,
                      "joined_steps": complete,
                      "live_ranks": live, "missing_ranks": missing,
                      "health": health}
        # gauges: the scrapeable face of the view
        _G_RANKS.set(len(all_ranks))
        _G_LIVE_RANKS.set(len(live))
        new_joined = complete - self._joined_seen
        if new_joined > 0:
            _C_JOINED.inc(new_joined)
            self._joined_seen = complete
        if skew:
            _G_SKEW.set(round(skew["step_ms"], 3))
            _G_BARRIER_SKEW.set(round(skew["barrier_wait_ms"], 3))
        if straggler is None:
            _G_STRAGGLER.set(-1)
            _G_CAUSE.set("none")
            _G_COMM_AXIS.set("none")
        else:
            _G_STRAGGLER.set(int(straggler["rank"]))
            _G_CAUSE.set(straggler["cause"])
            _G_COMM_AXIS.set(straggler.get("comm_axis") or "none")
        for ev in events:
            inc = ev["incident"]
            if ev["event"] == "open":
                _C_INCIDENTS.inc()
                _C_INCIDENT_CAUSE.get(
                    inc["cause"], _C_INCIDENT_CAUSE["unknown"]).inc()
                _logger().warning(
                    "cluster incident %d opened: rank %d is %.2fx the "
                    "peer median (%.2f ms over the last %d joined "
                    "steps); dominant cause: %s%s",
                    inc["id"], inc["rank"], inc["peak_ratio"],
                    inc["peak_step_ms"], self.window, inc["cause"],
                    (" on mesh axis '%s'" % inc["comm_axis"])
                    if inc.get("comm_axis") else "")
            elif ev["event"] == "close":
                _logger().info(
                    "cluster incident %d closed: rank %d (%s) after "
                    "%.1fs, rank_step %d..%d, peak %.2fx",
                    inc["id"], inc["rank"], inc["cause"],
                    inc["duration_s"], inc["start_rank_step"],
                    inc["end_rank_step"], inc["peak_ratio"])
        self._pending.extend(events)

    # -- hook dispatch / built-in remediation --------------------------------

    def _dispatch(self, ev: dict) -> None:
        """Fire one lifecycle event: built-in remediation first, then
        registered hooks.  Runs on the poll thread with the aggregator
        lock RELEASED, so a slow hook can never stall tailing — and
        never the step path.  Rate limiting is structural: the store
        emits each transition exactly once."""
        inc = ev["incident"]
        if ev["event"] == "escalate" and inc["cause"] == "input_bound":
            self._publish_advice(inc)
        with _LOCK:
            hooks = list(_HOOKS)
        if not hooks:
            return
        from . import tracing
        tracing.instant(f"cluster.incident.{ev['event']}",
                        incident=inc["id"], rank=inc["rank"],
                        cause=inc["cause"])
        for fn in hooks:
            try:
                fn(ev["event"], dict(inc))
            except Exception:
                _logger().exception("on_incident hook %r failed", fn)

    def _publish_advice(self, inc: dict) -> None:
        """First concrete remediation: a persistently input-bound rank
        should deepen its device prefetch ring.  The aggregator only
        PUBLISHES the advice record; the straggling rank's own
        SpoolSink applies it (opt-in, ``MXNET_REMEDIATE=1``).  At most
        one advice per incident (escalate fires once)."""
        try:
            from .data.device_pipeline import prefetch_depth
            depth = max(4, 2 * prefetch_depth())
        except Exception:
            depth = 4
        rec = {"action": "prefetch_depth", "rank": inc["rank"],
               "depth": int(depth), "incident_id": inc["id"],
               "cause": inc["cause"], "ts": round(time.time(), 3)}
        try:
            with open(os.path.join(self.directory, ADVICE_FILE),
                      "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            return
        _C_ADVICE_PUB.inc()
        _logger().warning(
            "remediation advice published (incident %d): rank %d "
            "input_bound -> DevicePrefetcher depth %d%s",
            inc["id"], inc["rank"], depth,
            "" if _remediate_enabled()
            else " (advisory; MXNET_REMEDIATE unset)")

    def view(self) -> dict:
        with self._lock:
            return dict(self._view)

    def health(self) -> Dict[int, dict]:
        """Per-rank health: healthy / degraded(cause) / missing, with
        last-seen age and last spool step."""
        with self._lock:
            return {r: dict(v) for r, v in self._health.items()}

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._run,
                                        name="mxnet-clustermon",
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            try:
                self.poll()
            except Exception:
                _logger().exception("cluster aggregator poll failed")

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(5.0)
        self._thread = None


_aggregator: Optional[ClusterAggregator] = None


def aggregator() -> Optional[ClusterAggregator]:
    """The live aggregator (rank 0 with MXNET_CLUSTER_DIR set), else
    None."""
    return _aggregator


def cluster_view() -> Optional[dict]:
    """The aggregator's current cluster view (None when not running)."""
    agg = _aggregator
    return agg.view() if agg is not None else None


def rank_health() -> Dict[int, dict]:
    """Per-rank health from the live aggregator — what the elastic
    restore barrier consults before deciding whether to keep waiting
    on a rank: ``{rank: {status: healthy|degraded|missing, cause,
    last_rank_step, since_s}}``.  Empty when no aggregator runs in
    this process (non-rank-0, or clustermon disabled)."""
    agg = _aggregator
    return agg.health() if agg is not None else {}


def incident_view(limit: int = 50) -> dict:
    """Open + recent closed incidents and per-cause counts — the JSON
    body ``GET /incidents`` serves on both scrape surfaces, merging the
    rank-0 aggregator's straggler store with any registered extra
    stores (serving SLO incidents).  Empty shape when neither runs in
    this process."""
    agg = _aggregator
    if agg is None:
        view = {"open": [], "recent": [], "counts": {}}
    else:
        with agg._lock:
            view = agg.incidents.snapshot(limit)
    with _LOCK:
        extras = list(_EXTRA_STORES)
    for store in extras:
        try:
            snap = store.snapshot(limit)
        except Exception:
            continue
        view["open"].extend(snap.get("open", ()))
        view["recent"].extend(snap.get("recent", ()))
        for cause, n in (snap.get("counts") or {}).items():
            view["counts"][cause] = view["counts"].get(cause, 0) + n
    if len(view["recent"]) > limit:
        view["recent"] = sorted(
            view["recent"],
            key=lambda i: i.get("end_ts") or i.get("start_ts") or 0
        )[-limit:]
    return view


def _on_cluster_dir(directory: Optional[str]) -> None:
    """telemetry's env-refresh hook: start/stop the aggregator as
    ``MXNET_CLUSTER_DIR`` appears/changes/vanishes.  Only the rank-0
    PROCESS runs one (the thread-local rank override is deliberately
    ignored: under threads-as-ranks any worker thread may trigger the
    env refresh, and the process as a whole is rank 0)."""
    global _aggregator
    if _aggregator is not None and \
            (directory is None or _aggregator.directory != directory):
        _aggregator.stop()
        _aggregator = None
    if directory and _aggregator is None and \
            _process_rank_world()[0] == 0:
        _aggregator = ClusterAggregator(directory)
        _aggregator.start()


# -- Prometheus text exposition ----------------------------------------------

_NAME_SANE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    return "mxnet_" + _NAME_SANE.sub("_", name)


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels(d: Dict[str, Any]) -> str:
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(d.items()))
    return "{" + inner + "}"


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return repr(f) if f == f else "NaN"


def prometheus_text(extra_labels: Optional[Dict[str, str]] = None) -> str:
    """The whole telemetry registry in Prometheus text exposition
    format (v0.0.4).  Every sample carries a ``rank`` label (the
    MegaScale-style per-rank metrics plane: one scrape config, rank as
    the aggregation dimension); counters render as ``counter``, gauges
    as ``gauge`` (string-valued gauges like ``cluster.straggler_cause``
    become a ``1``-valued sample with the string in a label), and
    histograms as ``summary`` — reservoir p50/p95 quantiles plus exact
    ``_sum``/``_count``.  The ``cluster.incidents_total.<cause>``
    counters fold into one ``mxnet_cluster_incidents_total`` family
    with a ``cause`` label; string gauges additionally re-emit every
    previously-seen value at 0 so a cleared cause doesn't latch in
    Prometheus."""
    r, _w = rank_world()
    base = dict(extra_labels or {})
    base["rank"] = str(r)
    out: List[str] = []
    typed: set = set()
    for name, m in telemetry.metrics().items():
        if isinstance(m, telemetry.Counter) and \
                name.startswith(_INCIDENTS_FAMILY):
            # one # TYPE line for the whole family; metrics() is sorted
            # by name so family members render adjacently
            pname = _metric_name(_INCIDENTS_FAMILY[:-1])
            if pname not in typed:
                typed.add(pname)
                out.append(f"# TYPE {pname} counter")
            cause = name[len(_INCIDENTS_FAMILY):]
            out.append(f"{pname}{_labels(dict(base, cause=cause))}"
                       f" {_fmt(m.value)}")
            continue
        pname = _metric_name(name)
        if isinstance(m, telemetry.Counter):
            out.append(f"# TYPE {pname} counter")
            out.append(f"{pname}{_labels(base)} {_fmt(m.value)}")
        elif isinstance(m, telemetry.Gauge):
            v = m.value
            if v is None:
                continue
            out.append(f"# TYPE {pname} gauge")
            if isinstance(v, str):
                key = "cause" if name.endswith("cause") else "value"
                with _LOCK:
                    seen = _STR_SEEN.setdefault(name, set())
                    seen.add(v)
                    vals = sorted(seen)
                for sv in vals:     # current at 1, stale series at 0
                    out.append(
                        f"{pname}{_labels(dict(base, **{key: sv}))}"
                        f" {1 if sv == v else 0}")
            else:
                out.append(f"{pname}{_labels(base)} {_fmt(v)}")
        elif isinstance(m, telemetry.Histogram):
            out.append(f"# TYPE {pname} summary")
            samples = sorted(m.samples())
            for q, qs in ((50, "0.5"), (95, "0.95")):
                if samples:
                    k = max(0, min(len(samples) - 1,
                                   round(q / 100 * (len(samples) - 1))))
                    out.append(f"{pname}{_labels(dict(base, quantile=qs))}"
                               f" {_fmt(samples[k])}")
            out.append(f"{pname}_sum{_labels(base)} {_fmt(m.total)}")
            out.append(f"{pname}_count{_labels(base)} {_fmt(m.count)}")
    return "\n".join(out) + "\n"


_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|NaN|[+-]Inf)"
    r"(?: -?[0-9]+)?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
    return (v.replace("\\\\", "\x00").replace("\\n", "\n")
            .replace('\\"', '"').replace("\x00", "\\"))


def parse_prometheus_text(text: str) -> Dict[str, List[Tuple[Dict[str,
                                                                  str],
                                                             float]]]:
    """Strict-ish exposition parser used by the tests and the CI
    scrape check: validates ``# TYPE`` lines and sample syntax, resolves
    label escapes, and requires every sample's base metric (modulo
    ``_sum``/``_count``/``_bucket`` suffixes) to have a preceding TYPE
    line.  Raises ``ValueError`` on any malformed line.  Returns
    {metric name: [(labels, value)]}."""
    types: Dict[str, str] = {}
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            if line.startswith("# HELP "):
                continue
            m = _TYPE_RE.match(line)
            if m is None:
                raise ValueError(f"line {i}: bad comment/TYPE line "
                                 f"{line!r}")
            types[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: bad sample line {line!r}")
        name, rawlabels, val = m.group(1), m.group(2), m.group(3)
        base = re.sub(r"_(sum|count|bucket)$", "", name)
        if name not in types and base not in types:
            raise ValueError(f"line {i}: sample {name!r} has no "
                             f"preceding # TYPE line")
        labels = {}
        if rawlabels:
            consumed = 0
            for lm in _LABEL_RE.finditer(rawlabels):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
                consumed = lm.end()
            if rawlabels[consumed:].strip(", "):
                raise ValueError(f"line {i}: bad label syntax "
                                 f"{rawlabels!r}")
        out.setdefault(name, []).append((labels, float(val)))
    return out


# -- standalone /metrics exporter (training processes) -----------------------

_metrics_httpd = None
_metrics_thread = None
_metrics_addr: Optional[Tuple[str, int]] = None


def start_metrics_server(port: int = 0,
                         host: str = "0.0.0.0") -> Tuple[str, int]:
    """Serve ``GET /metrics`` (text exposition), ``GET /incidents``
    (incident history JSON), ``GET /slo`` + ``GET /requestz`` (serving
    SLO view and slowest-request ring, when the serving subsystem is in
    this process) + ``GET /healthz`` on a daemon thread — the scrape
    surface for training processes, which have no serving server.
    Returns the bound ``(host, port)`` (OS-assigned when ``port=0``).
    Idempotent: an exporter already running keeps its socket."""
    global _metrics_httpd, _metrics_thread, _metrics_addr
    with _LOCK:
        if _metrics_httpd is not None:
            return _metrics_addr
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_GET(self):
                route = self.path.split("?", 1)[0]
                if route == "/metrics":
                    body = prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif route == "/incidents":
                    body = json.dumps(incident_view()).encode()
                    ctype = "application/json"
                elif route == "/slo":
                    from .serving import slo as _slo
                    body = json.dumps(_slo.slo_view()).encode()
                    ctype = "application/json"
                elif route == "/requestz":
                    from .serving import slo as _slo
                    limit = None
                    if "?" in self.path:
                        from urllib.parse import parse_qs
                        q = parse_qs(self.path.split("?", 1)[1])
                        try:
                            limit = int(q.get("limit", [None])[0])
                        except (TypeError, ValueError):
                            pass
                    body = json.dumps(_slo.requestz(limit)).encode()
                    ctype = "application/json"
                elif self.path == "/healthz":
                    view = cluster_view()
                    body = json.dumps(
                        {"status": "ok", "rank": rank_world()[0],
                         "world": rank_world()[1],
                         "cluster": view}).encode()
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        _metrics_httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        _metrics_httpd.daemon_threads = True
        _metrics_thread = threading.Thread(
            target=_metrics_httpd.serve_forever,
            name="mxnet-metrics-exporter", daemon=True)
        _metrics_thread.start()
        _metrics_addr = _metrics_httpd.server_address[:2]
        return _metrics_addr


def stop_metrics_server() -> None:
    global _metrics_httpd, _metrics_thread, _metrics_addr
    with _LOCK:
        httpd, thread = _metrics_httpd, _metrics_thread
        _metrics_httpd = _metrics_thread = _metrics_addr = None
    if httpd is not None:
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(5.0)


def metrics_server_address() -> Optional[Tuple[str, int]]:
    return _metrics_addr


def _on_metrics_port(port: Optional[str]) -> None:
    """telemetry's env-refresh hook for ``MXNET_METRICS_PORT``."""
    if not port:
        stop_metrics_server()
        return
    try:
        p = int(port)
    except ValueError:
        _logger().warning("invalid MXNET_METRICS_PORT=%r (want an int)",
                          port)
        return
    if _metrics_httpd is None:
        addr = start_metrics_server(p)
        _logger().info("metrics exporter serving /metrics on %s:%d",
                       *addr)
