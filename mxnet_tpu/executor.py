"""mx.executor — public Executor alias.

Parity: python/mxnet/executor.py (Executor wrapper over CachedOp); the
implementation lives with the Symbol API (symbol/executor.py — a
jit-backed executor), re-exported here under the reference's module
path.
"""
from .symbol.executor import Executor

__all__ = ["Executor"]
